"""North-star benchmark: ResNet-50 training throughput, img/s per chip.

Baseline (BASELINE.md / docs/faq/perf.md:214 in the reference): 298.51 img/s
on V100 fp32, bs=32 — MXNet 1.2 `train_imagenet.py`.

Un-losable by construction (round-3 postmortem: one slow sub-gate starved
the whole record): the primary metric is PRINTED the moment it is measured,
and a progressively extended full-JSON line is re-printed after every
sub-bench — every printed line is complete JSON, so whichever line is last
when the driver's clock runs out is a valid record (the reference's
benchmark_score.py prints per-model lines as it goes for the same reason).
Each sub-bench is time-boxed against a global budget
(MXTPU_BENCH_BUDGET_S); SIGTERM/SIGINT re-print the latest record before
exiting.  Exit code contract: 0 only when at least one measurement was
taken live THIS run — a run that only re-emitted carried-forward (stale)
numbers exits 1, so return-code consumers cannot mistake a dead run for
success (the in-record `stale`/`stale_keys` flags carry the detail).
"""
from __future__ import annotations

import glob
import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 298.51
_REPO_DIR = os.path.dirname(os.path.abspath(__file__))
_LKG_PATH = os.path.join(_REPO_DIR, "bench_lkg.json")


class _Record:
    """Accumulates the result dict; re-prints the full line after every
    update so the tail of stdout is always the most complete record."""

    def __init__(self, budget_s):
        self.result = {}
        self.t0 = time.monotonic()
        self.budget = budget_s
        self.stage_s = {}
        # carried-forward measurement keys not yet replaced by a live
        # value this run; mirrored into result["stale_keys"]
        self.stale_keys = set()
        self.measured_round = None
        # True once any measurement was taken THIS run (not carried
        # forward).  The exit code keys on it: a run killed before any
        # live measurement exits non-zero instead of reporting success
        # with a purely stale record (ADVICE r5 item 4).
        self.live = False
        # prebuilt line for the signal handler: print() is not
        # signal-safe (a SIGTERM landing mid-emit would raise
        # "reentrant call inside BufferedWriter" and tear the tail line)
        self.last_line = b""

    def remaining(self):
        return self.budget - (time.monotonic() - self.t0)

    def update_live(self, d):
        """Merge live measurements, clearing their staleness markers."""
        if d:
            self.live = True
        self.result.update(d)
        if self.stale_keys:
            self.stale_keys -= set(d)
            if self.stale_keys:
                self.result["stale_keys"] = sorted(self.stale_keys)
            else:
                self.result.pop("stale_keys", None)
        if not self.stale_keys and "stale" not in self.result:
            self.result.pop("stale_from_round", None)

    def emit(self):
        line = json.dumps(self.result)
        self.last_line = (line + "\n").encode()
        print(line, flush=True)
        # persist as last-known-good whenever the primary metric is live;
        # later stages keep refreshing the file so live inference numbers
        # reach it too (stale carried keys stay marked via stale_keys).
        # CPU runs never qualify — a validation run on the host must not
        # displace a TPU-measured record.
        if self.result.get("value") and not self.result.get("stale") \
                and self.result.get("backend_platform") != "cpu":
            try:
                lkg = {k: v for k, v in self.result.items()
                       if k != "stage_s"}
                if self.measured_round is not None:
                    lkg["measured_round"] = self.measured_round
                with open(_LKG_PATH, "w") as f:
                    json.dump(lkg, f)
            except OSError:
                pass

    def stage(self, name, est_s, fn):
        """Run one time-boxed sub-bench.  A stage that would not fit in the
        remaining budget is skipped (recorded, so the gap is visible); a
        stage that raises records its error; either way the record is
        re-emitted and later stages still run."""
        if self.remaining() < est_s:
            self.result.setdefault("skipped_stages", []).append(name)
            self.emit()
            return
        t = time.monotonic()
        try:
            self.update_live(fn() or {})
        except Exception as e:  # never lose earlier numbers
            self.result[name + "_error"] = str(e)[:200]
        self.stage_s[name] = round(time.monotonic() - t, 1)
        self.result["stage_s"] = self.stage_s
        self.emit()


def _bench_rounds_on_disk():
    rounds = [0]
    for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_r*.json")):
        m = re.search(r"BENCH_r0*(\d+)", p)
        if m:
            rounds.append(int(m.group(1)))
    return max(rounds)


def _load_last_good():
    """Last-known-good numbers: freshest of bench_lkg.json (written by the
    most recent successful run, possibly this session) and the driver's
    BENCH_r*.json records.  Records that are themselves pure carry-forwards
    (primary metric stale) are skipped — only measured values qualify as
    "known good".  Returns (round_or_None, parsed_dict) or None."""
    best = None  # key = (round, prefer_lkg)
    for p in glob.glob(os.path.join(_REPO_DIR, "BENCH_r*.json")):
        try:
            with open(p) as f:
                d = json.load(f)
        except Exception:
            continue
        parsed = d.get("parsed")
        if parsed and parsed.get("value") and not parsed.get("stale") \
                and parsed.get("backend_platform") != "cpu":
            m = re.search(r"BENCH_r0*(\d+)", p)
            rnd = int(m.group(1)) if m else -1
            if best is None or (rnd, 0) > best[0]:
                best = ((rnd, 0), parsed)
    try:
        with open(_LKG_PATH) as f:
            d = json.load(f)
        if d.get("value") and not d.get("stale") \
                and d.get("backend_platform") != "cpu":
            # within the same round a bench_lkg postdates the BENCH file
            rnd = d.get("measured_round", -1)
            if best is None or (rnd, 1) > best[0]:
                best = ((rnd, 1), d)
    except Exception:
        pass
    return (best[0][0], best[1]) if best else None


_PROBE_SRC = (
    # an explicit JAX_PLATFORMS must win over the site plugin's config
    # override (the tunnel plugin force-registers the TPU backend via
    # jax.config, which outranks the env var — tests/conftest.py note)
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "d = jax.devices(); print(d[0].platform, len(d))"
)


def _acquire_devices(rec, max_wait):
    """Backend acquisition that survives both failure modes seen in
    BENCH_r03/r04: a hard UNAVAILABLE raise and an indefinite hang inside
    the PJRT client init.  A subprocess probe (timeboxed, killable) is
    retried with the shared ``resilience.backoff`` policy (exponential
    with jitter, seeded for a replayable schedule) until the chip
    answers; only then does the main process initialise its own backend.
    Every failed attempt's error lands in ``backend_error_history`` so a
    dead round's record shows HOW the backend failed over time, not just
    the last message.  Returns a device list or None."""
    import jax

    from mxnet_tpu.resilience import chaos as _chaos
    from mxnet_tpu.resilience.backoff import BackoffPolicy

    t0 = time.monotonic()
    policy = BackoffPolicy(base_s=5.0, factor=1.7, max_delay_s=60.0,
                           max_retries=1000, jitter=0.2, seed=0)
    attempt = 0
    history = rec.result.setdefault("backend_error_history", [])
    del history[:]  # carried-forward history describes a previous round
    probe_timeout = float(os.environ.get("MXTPU_BENCH_PROBE_TIMEOUT", "150"))
    while True:
        attempt += 1
        # chaos probe: the harness stalls/faults backend init here — the
        # BENCH_r03..r05 hang, reproducible on demand
        _chaos.maybe_inject("backend.init", attempt)
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC], capture_output=True,
                text=True, timeout=min(probe_timeout,
                                       max(30.0, rec.remaining() - 30)))
            if out.returncode == 0:
                break
            err = (out.stderr or "").strip()[-300:]
        except subprocess.TimeoutExpired:
            err = "probe timeout (backend init hang)"
        except Exception as e:
            err = str(e)[:300]
        waited = time.monotonic() - t0
        rec.result["backend_error"] = err
        rec.result["backend_wait_s"] = round(waited, 1)
        rec.result["backend_attempts"] = attempt
        history.append({"attempt": attempt, "t_s": round(waited, 1),
                        "error": err[:120]})
        del history[:-12]  # keep the record line bounded
        rec.emit()
        delay = policy.delay(attempt - 1)
        if waited + delay > max_wait or rec.remaining() < 120:
            return None
        time.sleep(delay)
    # chip answered a fresh process; now init in-process (fast path)
    try:
        devices = jax.devices()
    except Exception as e:
        rec.result["backend_error"] = str(e)[:300]
        rec.emit()
        return None
    rec.result.pop("backend_error", None)
    if not rec.result.get("backend_error_history"):
        rec.result.pop("backend_error_history", None)
    rec.result["backend_attempts"] = attempt
    rec.result["backend_wait_s"] = round(time.monotonic() - t0, 1)
    rec.result["backend_platform"] = devices[0].platform
    return devices


def main():
    rec = _Record(float(os.environ.get("MXTPU_BENCH_BUDGET_S", "780")))

    def _bail(signum, frame):
        # async-signal-safe re-emit: raw write of the last complete line
        # (preceded by a newline in case a print was torn mid-line).
        # Exit 0 only when something was measured live this run: a run
        # killed on a purely carried-forward record must not report
        # success to anything keying on the return code.
        if rec.last_line:
            os.write(1, b"\n" + rec.last_line)
        os._exit(0 if rec.live else 1)

    signal.signal(signal.SIGTERM, _bail)
    signal.signal(signal.SIGINT, _bail)

    # last-known-good carried forward FIRST, before any jax/backend work:
    # whatever happens downstream, the driver's tail-line parse finds a
    # complete record (r03 rc=124 and r04 rc=1 both produced parsed:null
    # because nothing had been printed when the run died)
    lkg = _load_last_good()
    if lkg:
        rnd, parsed = lkg
        bookkeeping = {"measured_round", "stage_s", "backend_attempts",
                       "backend_wait_s", "backend_error_history",
                       "skipped_stages", "error"}
        carried = {k: v for k, v in parsed.items()
                   if not k.startswith("stale") and not k.endswith("_error")
                   and k not in bookkeeping}
        carried["stale"] = True
        if rnd is not None and rnd >= 0:
            carried["stale_from_round"] = rnd
        # every carried measurement stays marked until a live value
        # replaces it (the global "stale" flag covers only the primary
        # metric once training lands)
        rec.stale_keys = {k for k in carried
                          if k not in ("stale", "stale_from_round",
                                       "metric", "unit", "value",
                                       "vs_baseline")}
        if rec.stale_keys:
            carried["stale_keys"] = sorted(rec.stale_keys)
        rec.result.update(carried)
        rec.emit()
    # the round being measured: the driver writes BENCH_r{N} after this
    # run, so N = newest on disk + 1 (tags LKG provenance)
    rec.measured_round = _bench_rounds_on_disk() + 1

    try:
        _run_benches(rec)
    except Exception as e:  # never lose the tail record to a crash
        rec.result["fatal_error"] = str(e)[:300]
        rec.emit()
    # success == at least one live measurement this run; the record on
    # stdout is valid either way (stale flags say which)
    sys.exit(0 if rec.live else 1)


def _run_benches(rec):
    import jax

    # honor an explicit JAX_PLATFORMS over the site plugin's config-level
    # backend registration (same dance as the probe and tests/conftest.py)
    _plat = os.environ.get("JAX_PLATFORMS")
    if _plat:
        jax.config.update("jax_platforms", _plat)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    # persistent XLA compilation cache: the round-3 record died to compile
    # time (231s train-step + 355s infer compiles over the tunnel); a warm
    # cache turns every re-run into minutes.  Repo-local so the driver's
    # run hits the cache this session warmed.
    cache_dir = os.environ.get(
        "MXTPU_BENCH_CACHE_DIR", os.path.join(_REPO_DIR, ".jax_cache"))
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    # -- serving micro-bench FIRST: host-runnable (Runner->Batcher reqs/s
    # + p50/p99 latency, plus the fleet keys: per-tier p50/p99 under
    # mixed-model SLO-tiered load, shed_rate, swap_blip_ms — all on a
    # JAX_PLATFORMS=cpu subprocess), so the keys refresh even when the
    # TPU backend never comes up (the r5 failure mode: every key starved
    # behind backend acquisition)
    if os.environ.get("MXTPU_BENCH_SERVING", "1") == "1":
        rec.stage("serving", 150, _serving_bench)

    # -- input-pipeline micro-bench, ALSO host-only and BEFORE backend
    # acquisition: pipeline_fed_imgs_per_sec is a host property (decode +
    # shm transport + fenced feed with the fused uint8 tail), so it must
    # never starve behind a hung TPU init (the r03-r05 failure mode)
    if os.environ.get("MXTPU_BENCH_PIPELINE", "1") == "1":
        rec.stage("pipeline_host", 150, _pipeline_host_bench)

    # -- static cost model (mxcost), host-only and BEFORE backend
    # acquisition: modeled_step_flops/modeled_transfer_bytes come from an
    # abstract interpretation of the ResNet-50 training step's jaxpr —
    # no compile, no device — so they stay live when the TPU is down
    # (BENCH_r05: "backend unavailable after retries" left us with no
    # perf signal at all; the model is the signal of last resort)
    if os.environ.get("MXTPU_BENCH_STATIC_COST", "1") == "1":
        rec.stage("static_cost", 150, _static_cost_bench)

    # -- run-ahead overlap micro-bench, host-only and BEFORE backend
    # acquisition: train_loop_overlap_ratio (stepped vs bulk wall time on
    # CPU jax) keeps the async dispatch engine's win measurable when the
    # TPU is down (BENCH_r05: 4 stale keys because everything sat behind
    # backend acquisition)
    if os.environ.get("MXTPU_BENCH_OVERLAP", "1") == "1":
        rec.stage("overlap", 120, _overlap_bench)

    # -- fault-tolerance micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): recovery_time_s (checkpoint restore ->
    # first post-crash step) and checkpoint_overhead_pct (< 5% gate at
    # the default cadence) stay live when the TPU is down — resilience
    # numbers would be worthless if a dead backend could starve them
    if os.environ.get("MXTPU_BENCH_RESILIENCE", "1") == "1":
        rec.stage("resilience", 150, _resilience_bench)

    # -- elastic-tier micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): zero1_modeled_hbm_drop_pct (the ZeRO-1
    # memory win from the RUNTIME tape), reshard_restore_ms (the
    # resize-on-resume restore path) and supervisor_failover_steps_lost
    # (a real chaos SIGKILL -> shrink -> resume through the elastic
    # supervisor) stay live when the TPU is down
    if os.environ.get("MXTPU_BENCH_ELASTIC", "1") == "1":
        rec.stage("elastic", 150, _elastic_bench)

    # -- telemetry micro-bench, host-only and BEFORE backend acquisition
    # (r05 pattern): the observability layer's own cost must be provable
    # cheap — telemetry_overhead_pct (<= 1% gate), metrics_scrape_ms and
    # flight_recorder_write_ns stay live when the TPU is down
    if os.environ.get("MXTPU_BENCH_TELEMETRY", "1") == "1":
        rec.stage("telemetry", 150, _telemetry_bench)

    # -- mlops micro-bench, host-only and BEFORE backend acquisition
    # (r05 pattern): simulator_accuracy_pct (fleet simulator vs the real
    # host serving path, <= 15% error tolerance), promotion_decision_ms
    # (one full canary-judge tick) and capacity_replicas_for_1m_dau (the
    # pinned deterministic capacity answer) stay live when the TPU is
    # down — the production loop's own numbers must never starve behind
    # backend acquisition
    if os.environ.get("MXTPU_BENCH_MLOPS", "1") == "1":
        rec.stage("mlops", 150, _mlops_bench)

    # -- transformer mesh-tier micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): tp_modeled_model_axis_bytes (the pinned
    # fixture's tensor-parallel wire bytes), seqpar_tokens_per_sec_host
    # (a real data=2 x model=2 x sequence=2 train loop on the virtual
    # mesh) and tp_numerics_ok (mesh losses == replicated baseline) stay
    # live when the TPU is down — docs/transformer.md
    if os.environ.get("MXTPU_BENCH_TRANSFORMER", "1") == "1":
        rec.stage("transformer", 150, _transformer_bench)

    # -- pipeline-parallel micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): pp_modeled_bubble_frac +
    # pp_modeled_pipe_axis_bytes (the pinned pp_transformer_train_step
    # fixture's 1F1B schedule geometry), pp_tokens_per_sec_host (a real
    # pipe=2 x model=2 x data=2 train loop on the virtual mesh) and
    # pp_numerics_ok (pipelined losses == replicated baseline) stay
    # live when the TPU is down — docs/pipeline.md
    if os.environ.get("MXTPU_BENCH_PP", "1") == "1":
        rec.stage("pipeline_parallel", 150, _pp_bench)

    # -- fusion-tier micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): fused_optimizer_speedup_host (measured
    # unfused per-param update vs the fused flat Pallas kernel on the
    # 1-core host), modeled_fusion_bytes_saved_pct (the fusion pass's
    # deterministic win over the optimizer chain) and fusion_numerics_ok
    # (fused == unfused Optimizer.update within tolerance, bitwise
    # rerun) stay live when the TPU is down — docs/fusion.md
    if os.environ.get("MXTPU_BENCH_FUSION", "1") == "1":
        rec.stage("fusion", 150, _fusion_bench)

    # -- codegen-tier micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): codegen_generated_speedup_host
    # (measured op-at-a-time unfused chain vs the mxgen generated
    # Pallas kernel, summed over the shipped chains),
    # codegen_modeled_bytes_saved_pct (the lowering's deterministic
    # byte win — the codegen_chains budget rows) and
    # codegen_numerics_ok (generated == tape reference through the real
    # pallas path, bitwise rerun) stay live when the TPU is down —
    # docs/fusion.md "Generated kernels"
    if os.environ.get("MXTPU_BENCH_CODEGEN", "1") == "1":
        rec.stage("codegen", 150, _codegen_bench)

    # -- decode-tier micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): decode_tokens_per_sec_host (continuous
    # batching through the DecodeRunner→DecodeBatcher path under a
    # seeded concurrent mixed-length burst), decode_p99_per_token_ms
    # (the SLO unit of the tokens-remaining shed arithmetic),
    # decode_numerics_ok (paged-cache greedy decode == the no-cache
    # full-forward reference, exactly) and decode_recompiles (zero
    # steady-state jit-cache growth over the prefill-bucket × decode-
    # slot surface) stay live when the TPU is down — docs/serving.md
    if os.environ.get("MXTPU_BENCH_DECODE", "1") == "1":
        rec.stage("decode", 150, _decode_bench)

    # -- mixed-precision micro-bench, host-only and BEFORE backend
    # acquisition (r05 pattern): fused_loss_scaled_speedup_host (the
    # unscale+clip+update+select-skip chain vs the one-pass fused
    # kernel), bf16_modeled_hbm_ratio (deterministic, from the
    # bf16_zero1_train_step budget builder), bf16_convergence_delta
    # (bf16 vs f32 loss trajectories, same seed) and
    # int8_kv_decode_tokens_per_sec_host (+ token agreement with the
    # f32 cache) stay live when the TPU is down — docs/precision.md.
    # NOTE: MXTPU_BENCH_PRECISION (no _STAGE) is the matmul-precision
    # knob below; the stage toggle is deliberately distinct.
    if os.environ.get("MXTPU_BENCH_PRECISION_STAGE", "1") == "1":
        rec.stage("precision", 150, _precision_bench)

    # default 256/chip: the reference's headline number is bs=32-per-GPU,
    # but modern chips need larger batches to fill the MXU — measured on
    # one chip (bf16): bs=128 → ~2000, bs=256 → ~2300, bs=512 → ~2250
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "256"))
    # keep the per-chip metric honest: batch is per chip, and the device
    # count matches the mesh the trainer actually spans
    devices = _acquire_devices(
        rec, max_wait=float(os.environ.get("MXTPU_BENCH_BACKEND_WAIT_S",
                                           "600")))
    if devices is None:
        # backend never came up: the carried-forward record (already on
        # the wire) is the round's result; say so and stop cleanly
        rec.result["error"] = "backend unavailable after retries"
        rec.emit()
        return
    n_dev = len(devices)
    mesh = make_mesh((n_dev,), ("data",), devices)
    global_batch = batch * n_dev

    # end-to-end bf16 training: bf16 activations/params with fp32 master
    # weights in the optimizer (multi_precision) — the TPU-native analogue of
    # the reference's fp16 path (docs/faq/perf.md fp16 rows).  BN statistics
    # stay fp32 (BatchNorm.cast).  MXTPU_BENCH_DTYPE=float32 forces full
    # precision.
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    # NHWC is the TPU-native conv layout (channels on the minor axis)
    layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")
    # MXU precision for fp32 matmuls/convs; MXTPU_BENCH_PRECISION=float32
    # (with MXTPU_BENCH_DTYPE=float32) forces a true full-precision run
    precision = os.environ.get("MXTPU_BENCH_PRECISION", "bfloat16")
    jax.config.update("jax_default_matmul_precision", precision)

    rng = np.random.RandomState(0)

    def make_batch(b):
        shape = (b, 3, 224, 224) if layout == "NCHW" else (b, 224, 224, 3)
        x = rng.rand(*shape).astype(np.float32)
        return (mx.nd.array(x).astype(dtype),
                mx.nd.array((rng.rand(b) * 1000).astype(np.int64)))

    def build_trainer():
        # rebuilt from scratch on every OOM retry: the step jit donates the
        # parameter/state buffers, so a failed step may have invalidated them
        net = vision.resnet50_v1(layout=layout)
        net.initialize(mx.init.Xavier())
        net.cast(dtype)
        return DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4,
             "multi_precision": dtype != "float32"}, mesh=mesh)

    # warmup (compile); halve the batch on OOM so the metric always prints.
    # Any other failure records the error and falls through to the infer
    # stages — the carried-forward train number stays on the wire.
    trainer = None
    imgs_per_sec_per_chip = None
    t_warm = time.monotonic()
    try:
        while True:
            try:
                trainer = build_trainer()
                x, y = make_batch(global_batch)
                for _ in range(3):
                    trainer.step(x, y).asscalar()
                break
            except Exception as e:  # RESOURCE_EXHAUSTED etc.
                if "RESOURCE_EXHAUSTED" not in str(e) or batch <= 8:
                    raise
                batch //= 2
                global_batch = batch * n_dev
        rec.stage_s["train_compile"] = round(time.monotonic() - t_warm, 1)

        iters = int(os.environ.get("MXTPU_BENCH_ITERS", "10"))
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = trainer.step(x, y)
        loss.asscalar()  # sync
        dt = time.perf_counter() - t0
        imgs_per_sec_per_chip = global_batch * iters / dt / n_dev
    except Exception as e:
        rec.result["train_error"] = str(e)[:300]
        rec.emit()

    if imgs_per_sec_per_chip is not None:
        # a live primary metric replaces the carried-forward one; the
        # remaining carried sub-bench numbers stay listed in stale_keys
        # until their stages refresh them
        for k in ("stale", "error", "train_error", "fatal_error",
                  "backend_error"):
            rec.result.pop(k, None)
        rec.update_live({
            "metric": "resnet50_train_imgs_per_sec_per_chip",
            "value": round(imgs_per_sec_per_chip, 2),
            "unit": "img/s/chip",
            "vs_baseline": round(
                imgs_per_sec_per_chip / BASELINE_IMGS_PER_SEC, 3),
        })
        # modeled-vs-measured: the static cost model's flops/img times the
        # measured rate = achieved model-TFLOP/s; against the chip's peak
        # (MXTPU_PEAK_TFLOPS, default 197 = v5e bf16) that is a modeled
        # MFU — a perf regression shows up as a falling ratio even when
        # absolute img/s moved for unrelated reasons (batch, host)
        fpi = rec.result.get("modeled_flops_per_img")
        if fpi:
            achieved = fpi * imgs_per_sec_per_chip / 1e12
            rec.update_live({
                "modeled_achieved_tflops_per_chip": round(achieved, 3),
                "modeled_mfu": round(achieved / float(os.environ.get(
                    "MXTPU_PEAK_TFLOPS", "197")), 4),
            })
        rec.result["stage_s"] = rec.stage_s
        rec.emit()  # primary metric on the wire (and into bench_lkg.json)

    # -- pipeline-fed measurement (reference: train_imagenet.py feeds the
    # trainer through ImageRecordIter, src/io/iter_image_recordio_2.cc).
    # A synthetic JPEG .rec is packed on the fly; both the iterator-only
    # rate (native decode) and the trainer-fed rate are reported.  On this
    # host the decode path is CPU-bound (os.cpu_count() cores drive
    # libjpeg), so the pipeline rate is a host property, not a chip one.
    if os.environ.get("MXTPU_BENCH_PIPELINE", "1") == "1":
        synth = (imgs_per_sec_per_chip * n_dev
                 if imgs_per_sec_per_chip else None)
        rec.stage("pipeline", 45, lambda: _pipeline_bench(
            trainer, batch, layout, dtype, synth_rate=synth))

    # -- inference: bf16 denominator + int8 (reference: benchmark_score.py
    # fp32/fp16 table in docs/faq/perf.md:156,170, and quantized resnet via
    # quantize_graph_pass.cc + quantized_conv/pooling/fc kernels).
    run_bf16 = os.environ.get("MXTPU_BENCH_BF16", "1") == "1"
    run_int8 = os.environ.get("MXTPU_BENCH_INT8", "1") == "1"
    if run_bf16 or run_int8:
        # drop the trainer's HBM (params, fp32 masters, momentum,
        # donated activations) before binding the inference executors
        trainer = None
        import gc
        gc.collect()
    if run_bf16:
        rec.stage("bf16_infer", 60, _bf16_infer_bench)
    if run_int8:
        # perf first (cheap: quantize with naive calibration on an untrained
        # net would skew accuracy, so the full gate below re-quantizes with
        # entropy calibration on a trained net — but the THROUGHPUT number
        # does not depend on the weights' values, so it is measured first
        # and survives even if the accuracy gate is cut off)
        rec.stage("int8_infer", 90, _int8_infer_bench)
        rec.stage("int8_acc", 150, _int8_accuracy_gate)


def _pipeline_host_bench():
    """Host-only pipeline rates through mxnet_tpu.io.bench: legacy float
    path vs the multi-process uint8 pipeline with the fused device tail,
    plus the worker-scaling curve.  JAX_PLATFORMS=cpu subprocess — same
    isolation contract as the serving stage."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.io.bench"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("pipeline bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _static_cost_bench():
    """Hardware-free modeled cost of the ResNet-50 training step via the
    mxcost CLI (JAX_PLATFORMS=cpu subprocess, same isolation contract as
    the serving/pipeline stages), plus the mxshard proof numbers from
    the sharded budget models: modeled_zero1_hbm_drop_pct (the ZeRO-1
    peak-HBM saving vs the replicated twin on the declared 8-way mesh)
    and modeled_ring_attn_collective_bytes (the ppermute ring schedule
    of parallel/ring_attention.py) — both deterministic, both gated by
    tools/bench_compare.py from r06 onward.  The resnet model traces at
    batch 32; flops scale linearly in batch so flops/img is
    geometry-free."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")

    def run_cli(models, extra=()):
        out = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.analysis", "--cost",
             "--json", "--model", models] + list(extra),
            capture_output=True, text=True, timeout=300, env=env,
            cwd=_REPO_DIR)
        if out.returncode != 0 or not out.stdout.strip():
            raise RuntimeError("static cost rc=%d: %s" % (
                out.returncode, (out.stderr or out.stdout).strip()[-200:]))
        return json.loads(out.stdout)

    payload = run_cli("resnet50_train_step")
    cost = payload["cost"]["resnet50_train_step"]
    batch = 32  # the budget model's pinned trace geometry
    result = {
        "modeled_step_flops": int(cost["flops"]),
        "modeled_flops_per_img": int(cost["flops"] // batch),
        "modeled_transfer_bytes": int(cost["transfer_bytes"]),
        "modeled_peak_hbm_bytes": int(cost["peak_hbm_bytes"]),
        "modeled_collective_bytes": int(cost["collective_bytes"]),
    }
    sharded = run_cli("zero1_mlp_train_step,ring_attention_fwd",
                      extra=["--shard"])
    reports = sharded.get("shard", {}).get("reports", {})
    zero1 = reports.get("zero1_mlp_train_step", {}).get("extras", {})
    ring = reports.get("ring_attention_fwd", {}).get("extras", {})
    if "modeled_zero1_hbm_drop_pct" in zero1:
        result["modeled_zero1_hbm_drop_pct"] = float(
            zero1["modeled_zero1_hbm_drop_pct"])
    if "modeled_ring_attn_collective_bytes" in ring:
        result["modeled_ring_attn_collective_bytes"] = int(
            ring["modeled_ring_attn_collective_bytes"])
    return result


def _overlap_bench():
    """Stepped-vs-bulk training-loop wall time through the run-ahead
    engine (mxnet_tpu/engine_bench.py): train_loop_overlap_ratio +
    dispatch_depth + dispatch-stall counters.  JAX_PLATFORMS=cpu
    subprocess — same isolation contract as the serving/pipeline/cost
    stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.engine_bench"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("overlap bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _telemetry_bench():
    """telemetry_overhead_pct (trainer step loop with the telemetry
    layer armed vs off, interleaved min-of-N windows — the <= 1% gate),
    metrics_scrape_ms (one Prometheus scrape over a populated registry)
    and flight_recorder_write_ns (one mmap ring record) through
    mxnet_tpu/telemetry/bench.py.  JAX_PLATFORMS=cpu subprocess — same
    isolation contract as the serving/pipeline/cost/overlap/resilience
    stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry.bench"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("telemetry bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _mlops_bench():
    """simulator_accuracy_pct (discrete-event fleet simulator vs the
    real host serving path under the parked-burst scenario),
    promotion_decision_ms (a real train->canary->promote cycle's
    terminal decision tick) and capacity_replicas_for_1m_dau (the
    pinned deterministic capacity computation) through
    mxnet_tpu/mlops/bench.py.  JAX_PLATFORMS=cpu subprocess — same
    isolation contract as the serving/pipeline/cost stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.mlops.bench"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("mlops bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _resilience_bench():
    """recovery_time_s + checkpoint_overhead_pct through the resilience
    harness (mxnet_tpu/resilience/bench.py): an MLP trainer is stepped
    with and without auto-checkpointing at the default cadence, then
    crash-resumed from the snapshot, asserting bitwise-identical params.
    The same stage reports the PS server durability tier:
    server_recovery_time_s (snapshot load + WAL replay of a crashed
    PSServer's state dir), wal_replay_rate_keys_per_s and the
    snapshot/WAL overhead split.  JAX_PLATFORMS=cpu subprocess — same
    isolation contract as the serving/pipeline/cost/overlap stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.resilience.bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("resilience bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _elastic_bench():
    """zero1_modeled_hbm_drop_pct + reshard_restore_ms +
    supervisor_failover_steps_lost through the elastic harness
    (mxnet_tpu/resilience/elastic_bench.py): the runtime-tape ZeRO-1
    memory proof, a 4-way shard checkpoint restored into a 2-way
    trainer (bitwise-checked), and a real supervisor failover (chaos
    SIGKILL of 1-of-2 ranks, auto-shrink + resume, steps_lost from the
    audit record).  JAX_PLATFORMS=cpu subprocess with a 4-device
    virtual mesh — same isolation contract as the other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the reshard stage needs a 4-way virtual mesh in the child
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.resilience.elastic_bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("elastic bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _transformer_bench():
    """tp_modeled_model_axis_bytes + seqpar_tokens_per_sec_host +
    tp_numerics_ok through the transformer mesh-tier harness
    (mxnet_tpu/transformer/bench.py): the pinned
    tp_transformer_train_step fixture's per-axis modeled schedule, a
    real 2x2x2 mesh train loop on an 8-device virtual host mesh, and
    the mesh-vs-replicated loss-parity contract.  JAX_PLATFORMS=cpu
    subprocess — same isolation contract as the other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the 2x2x2 mesh needs an 8-way virtual device pool in the child
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.transformer.bench"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("transformer bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _pp_bench():
    """pp_modeled_bubble_frac + pp_modeled_pipe_axis_bytes +
    pp_tokens_per_sec_host + pp_numerics_ok through the pipeline-tier
    harness (mxnet_tpu/transformer/pp_bench.py): the pinned
    pp_transformer_train_step fixture's modeled 1F1B schedule, a real
    pipe=2 x model=2 x data=2 train loop on an 8-device virtual host
    mesh, and the pipelined-vs-replicated loss-parity contract.
    JAX_PLATFORMS=cpu subprocess — same isolation contract as the
    other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the pipe=2 x model=2 x data=2 mesh needs an 8-way virtual pool
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.transformer.pp_bench"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("pipeline bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _fusion_bench():
    """fused_optimizer_speedup_host + modeled_fusion_bytes_saved_pct +
    fusion_numerics_ok through the fusion-tier harness
    (mxnet_tpu/fusion_bench.py): the measured unfused-vs-fused
    optimizer update wall time on the host, the deterministic modeled
    bytes-saved of the optimizer chain, and the fused-vs-unfused
    numerics contract.  JAX_PLATFORMS=cpu subprocess — same isolation
    contract as the other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual test mesh in the child
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.fusion_bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("fusion bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _codegen_bench():
    """codegen_generated_speedup_host + codegen_modeled_bytes_saved_pct
    + codegen_numerics_ok through the codegen-tier harness
    (mxnet_tpu/codegen_bench.py): the measured unfused-chain vs
    generated-kernel wall time on the host, the mxgen lowering's
    deterministic bytes-saved, and the generated-vs-reference numerics
    contract.  JAX_PLATFORMS=cpu subprocess — same isolation contract
    as the other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual test mesh in the child
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.codegen_bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("codegen bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _precision_bench():
    """fused_loss_scaled_speedup_host + bf16_modeled_hbm_ratio +
    bf16_convergence_delta + int8_kv_decode_tokens_per_sec_host +
    precision_numerics_ok through the mixed-precision harness
    (mxnet_tpu/precision_bench.py).  JAX_PLATFORMS=cpu subprocess —
    same isolation contract as the other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual test mesh in the child
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.precision_bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("precision bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _decode_bench():
    """decode_tokens_per_sec_host + per-token latency percentiles +
    decode_numerics_ok + decode_recompiles through the autoregressive
    serving harness (mxnet_tpu/serving/decode_bench.py): a seeded
    concurrent mixed-length burst continuous-batched through the
    DecodeRunner→DecodeBatcher path over the paged KV cache, with the
    cached-vs-full-forward numerics contract and the zero-recompile
    contract gated by the child's rc.  JAX_PLATFORMS=cpu subprocess —
    same isolation contract as the other host stages."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual test mesh in the child
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving.decode_bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("decode bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _serving_bench():
    """serving_reqs_per_sec + request-latency percentiles through the full
    ModelRunner->Batcher path, and the fleet keys (per-tier p50/p99 under
    mixed-model SLO-tiered load with a degraded-mode fallback,
    shed_rate, swap_blip_ms) — mxnet_tpu/serving/bench.py.  Runs as a
    JAX_PLATFORMS=cpu subprocess: host-capable by construction, and a
    hung TPU backend in THIS process can never starve it."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual test mesh in the child
    env["PYTHONPATH"] = _REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving.bench"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=_REPO_DIR)
    if out.returncode != 0 or not out.stdout.strip():
        raise RuntimeError("serving bench rc=%d: %s" % (
            out.returncode, (out.stderr or out.stdout).strip()[-200:]))
    return json.loads(out.stdout.strip().splitlines()[-1])


def _bf16_infer_bench(batch=None, iters=20):
    """bf16 inference denominator (reference: benchmark_score.py, the fp16
    row of docs/faq/perf.md:170) — NHWC bf16 jitted forward, bs>=64."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    batch = batch or int(os.environ.get("MXTPU_BENCH_INFER_BATCH", "256"))
    rng = np.random.RandomState(0)
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    net.hybridize()
    x = mx.nd.array(rng.rand(batch, 224, 224, 3).astype(np.float32)) \
        .astype("bfloat16")
    out = net(x)
    out.asnumpy()  # compile + hard sync (device->host round-trip; the
    # axon tunnel's block_until_ready is not a reliable fence)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = net(x)
    out.asnumpy()
    dt = time.perf_counter() - t0
    return {"bf16_infer_imgs_per_sec": round(batch * iters / dt, 2)}


def _blob_images(rng, n, nclass=8, size=224):
    """Class-separable synthetic images — gives the accuracy gate a
    functioning classifier to quantize instead of argmax roulette on
    near-uniform untrained logits (shared impl: test_utils)."""
    from mxnet_tpu.test_utils import separable_images
    return separable_images(rng, n, nclass=nclass, size=size, channels=3,
                            noise=0.3, base=0.8)


def _quantized_resnet50(arg=None, aux=None, calib_it=None, calib_batch=64,
                        calib_mode="entropy"):
    """Quantize a ResNet-50 symbol (NHWC end to end so the int8 convs/dots
    land on the MXU int8 path without transposes).

    The stem conv IS quantized here (the reference excludes conv0 by
    default, accuracy-motivated): measured r4 on v5e, the fp32 stem cost
    ~10% e2e (8878 -> 9736 img/s with it quantized) and the accuracy
    gate's <=1% drop bound still holds with entropy calibration.  Two
    rejected levers, both measured slower: a bf16 float rail
    (MXTPU_INT8_FLOAT=bfloat16, 6783 — bf16<->int8 retiling beats the
    fp32 it saves) and XLA-fused requantize (MXTPU_FUSE_QCONV=1, 6049 —
    fusing the epilogue into the conv loses the conv's tiling)."""
    import mxnet_tpu as mx
    from mxnet_tpu.symbol.models import resnet_symbol

    net = resnet_symbol(50, num_classes=8, layout="NHWC")
    if arg is None:
        # shape-only init: threshold values don't change the compiled
        # int8 program's speed, just its scales.  Random (not zero) calib
        # data so every activation range is non-degenerate.
        mod = mx.mod.Module(net)
        rng = np.random.RandomState(0)
        it = mx.io.NDArrayIter(
            rng.rand(calib_batch, 224, 224, 3).astype(np.float32),
            np.zeros(calib_batch, np.float32), calib_batch)
        mod.bind(it.provide_data, it.provide_label)
        mod.init_params(mx.init.Xavier())
        arg, aux = mod.get_params()
        calib_it = it
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=calib_it,
        num_calib_examples=calib_batch, calib_mode=calib_mode,
        excluded_sym_names=os.environ.get(
            "MXTPU_INT8_EXCLUDE", "").split(",")
        if os.environ.get("MXTPU_INT8_EXCLUDE") else [])
    return net, arg, aux, qsym, qarg, qaux


def _bf16_data_desc(provide_data):
    """Rebind descriptors with bf16 data so bind-time type inference puts
    the whole float rail (stem, biases, elementwise chains) on bf16 —
    init_params then casts the fp32 checkpoint values to the inferred
    dtypes automatically (module.py init_params)."""
    import jax.numpy as jnp

    import mxnet_tpu as mx
    if os.environ.get("MXTPU_INT8_FLOAT") != "bfloat16":
        return provide_data
    return [mx.io.DataDesc(d.name, d.shape, dtype=jnp.bfloat16,
                           layout=getattr(d, "layout", "NCHW"))
            for d in provide_data]


def _int8_infer_bench(batch=None, iters=20):
    """int8 inference throughput only — Xavier weights, naive calibration
    (the compiled program and hence the rate are weight-independent)."""
    import gc

    import mxnet_tpu as mx

    gc.collect()  # drop the bf16 executor's HBM (Block cycles) first
    batch = batch or int(os.environ.get("MXTPU_BENCH_INFER_BATCH", "256"))
    rng = np.random.RandomState(0)
    _, _, _, qsym, qarg, qaux = _quantized_resnet50(calib_mode="naive")
    Xb = rng.rand(batch, 224, 224, 3).astype(np.float32)
    it = mx.io.NDArrayIter(Xb, np.zeros(batch, np.float32), batch)
    qmod = mx.mod.Module(qsym)
    qmod.bind(_bf16_data_desc(it.provide_data), it.provide_label,
              for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    # bf16 batch: the excluded stem then runs on the bf16 rail end to end
    xdev = mx.nd.array(Xb)
    if os.environ.get("MXTPU_INT8_FLOAT") == "bfloat16":
        xdev = xdev.astype("bfloat16")
    b = mx.io.DataBatch(data=[xdev], label=[])
    qmod.forward(b, is_train=False)
    qmod.get_outputs()[0].asnumpy()  # compile + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        qmod.forward(b, is_train=False)
    qmod.get_outputs()[0].asnumpy()
    dt = time.perf_counter() - t0
    return {"int8_infer_imgs_per_sec": round(batch * iters / dt, 2)}


def _int8_accuracy_gate(batch=None, calib_batch=64, eval_images=1024,
                        train_images=2048, epochs=5):
    """Accuracy gate: train ResNet-50 to competence on separable synthetic
    data, quantize with entropy calibration + BN folding, check int8 top-1
    within 1% of fp32 on 1000+ images (VERDICT r2 gate).  Runs AFTER the
    throughput stages so its cost can never starve them."""
    import gc

    import mxnet_tpu as mx

    gc.collect()  # drop the previous stage's executors before binding
    batch = batch or int(os.environ.get("MXTPU_BENCH_INFER_BATCH", "256"))
    rng = np.random.RandomState(0)
    Xtr, ytr = _blob_images(rng, train_images)
    train_it = mx.io.NDArrayIter(Xtr, ytr, 128, shuffle=True,
                                 shuffle_seed=3)
    from mxnet_tpu.symbol.models import resnet_symbol
    net = resnet_symbol(50, num_classes=8, layout="NHWC")
    mod = mx.mod.Module(net)
    # adam + seeded shuffle + seeded init: short from-scratch sgd on
    # resnet-50 sat on a knife edge where run-to-run noise decided
    # whether the gate's classifier converged at all
    mx.random.seed(11)
    np.random.seed(11)
    mod.fit(train_it, num_epoch=epochs, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3})
    arg, aux = mod.get_params()
    calib_it = mx.io.NDArrayIter(Xtr[:calib_batch], ytr[:calib_batch],
                                 calib_batch)
    # entropy (KL) calibration + BN folding — the round-3 int8 pipeline;
    # same recipe as the throughput stage (shared helper) so the gated
    # accuracy describes the benchmarked program
    net, arg, aux, qsym, qarg, qaux = _quantized_resnet50(
        arg, aux, calib_it, calib_batch=calib_batch)

    # fp32 eval predictions captured BEFORE the fp32 executor is dropped
    # so it never coexists with the int8 one in HBM
    Xev, yev = _blob_images(np.random.RandomState(7), eval_images)
    eval_sets = [(Xev[s:s + batch], yev[s:s + batch])
                 for s in range(0, eval_images, batch)]
    fp32_preds = []
    fp32_correct = 0
    infer_mod = mx.mod.Module(net)
    it0 = mx.io.NDArrayIter(Xev[:batch], yev[:batch], batch)
    infer_mod.bind(it0.provide_data, it0.provide_label, for_training=False)
    infer_mod.set_params(arg, aux)
    for Xe, ye in eval_sets:
        eb = mx.io.DataBatch(data=[mx.nd.array(Xe)], label=[])
        infer_mod.forward(eb, is_train=False)
        pred = infer_mod.get_outputs()[0].asnumpy().argmax(1)
        fp32_preds.append(pred)
        fp32_correct += int((pred == ye).sum())
    mod = infer_mod = None
    import gc
    gc.collect()

    it = mx.io.NDArrayIter(Xev[:batch], yev[:batch], batch)
    qmod = mx.mod.Module(qsym)
    # same binding as the throughput stage: the gate must validate the
    # exact program the benchmark times (incl. any bf16 rail)
    qmod.bind(_bf16_data_desc(it.provide_data), it.provide_label,
              for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    bf16_rail = os.environ.get("MXTPU_INT8_FLOAT") == "bfloat16"
    agree = tot = int8_correct = 0
    for (Xe, ye), ref in zip(eval_sets, fp32_preds):
        xe = mx.nd.array(Xe)
        if bf16_rail:
            xe = xe.astype("bfloat16")
        eb = mx.io.DataBatch(data=[xe], label=[])
        qmod.forward(eb, is_train=False)
        got = qmod.get_outputs()[0].asnumpy().argmax(1)
        agree += int((ref == got).sum())
        int8_correct += int((got == ye).sum())
        tot += len(got)
    return {
        "int8_top1_agreement": round(agree / tot, 4),
        "fp32_top1_acc": round(fp32_correct / tot, 4),
        "int8_top1_acc": round(int8_correct / tot, 4),
        "int8_top1_drop": round((fp32_correct - int8_correct) / tot, 4),
    }


def _pipeline_bench(trainer, batch, layout, dtype, n_records=None,
                    synth_rate=None):
    import io as _pyio
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    n_records = n_records or int(os.environ.get("MXTPU_BENCH_PIPELINE_N",
                                                "1024"))
    tmpdir = tempfile.mkdtemp(prefix="mxtpu_bench_rec_")
    rec_path = os.path.join(tmpdir, "synth.rec")
    idx_path = os.path.join(tmpdir, "synth.idx")
    rng = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    buf = _pyio.BytesIO()
    for i in range(n_records):
        img = rng.randint(0, 255, (224, 224, 3), np.uint8)
        buf.seek(0)
        buf.truncate()
        Image.fromarray(img).save(buf, format="JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        writer.write_idx(i, recordio.pack(header, buf.getvalue()))
    writer.close()

    # uint8 + NHWC: the decoder's own layout, so the host does zero
    # transpose/cast work and the host->device transfer is 4x narrower
    # than fp32; normalization fuses into the device program.
    # NOTE the iterator produces batches whose nd.array already *dispatches*
    # the h2d transfer; rates below differ by what they wait for:
    #   decode rate  — host decode+assembly only (no transfer fence)
    #   feed rate    — decode + transfer fenced on device (DeviceFeedIter):
    #                  the true rate at which the device can be fed
    #   fed rate     — full training consuming the device feed
    def make_it():
        return mx.io.ImageRecordIter(
            path_imgrec=rec_path, path_imgidx=idx_path,
            data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
            dtype="uint8", layout="NHWC" if layout == "NHWC" else "NCHW")

    # pure host decode rate + decode-thread scaling harness (reference:
    # preprocess_threads / the OMP decode team in
    # iter_image_recordio_2.cc:139): native libjpeg decode of the whole
    # record set, no device dispatch in the loop (an iterator-based
    # measure would include h2d transfer backpressure and measure the
    # tunnel, not the host).  On a 1-core host the thread curve is flat —
    # the harness proves the architecture.
    from mxnet_tpu import _native
    scaling = {}
    decode_rate = 0.0
    if _native.available():
        reader = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
        all_bufs = [recordio.unpack(reader.read_idx(i))[1]
                    for i in range(n_records)]
        reader.close()
        t0 = time.perf_counter()
        _native.decode_batch(all_bufs, 224, 224, 3)
        decode_rate = round(n_records / (time.perf_counter() - t0), 2)
        for nt in (1, 2, 4):
            t0 = time.perf_counter()
            _native.decode_batch(all_bufs[:batch], 224, 224, 3,
                                 num_threads=nt)
            scaling[str(nt)] = round(batch / (time.perf_counter() - t0), 2)

    prep = jax.jit(lambda x: (x.astype(jnp.float32) / 255.0).astype(dtype))
    # warm the prep jit so its compile (tens of seconds) never lands
    # inside a timed window
    import numpy as _np
    prep(jnp.asarray(_np.zeros((batch, 224, 224, 3), _np.uint8))) \
        .block_until_ready()

    # feed rate: decode + fenced device transfer, no training.  The timer
    # starts BEFORE the iterator is built: its worker begins prefetching
    # at construction, and with only ~4 batches the warm prefetch would
    # otherwise hide most of the feed work.
    t0 = time.perf_counter()
    feed = mx.io.DeviceFeedIter(make_it(), transform=prep)
    n_feed = 0
    for b in feed:
        n_feed += b.data[0].shape[0]
    dt_feed = time.perf_counter() - t0
    feed_rate = n_feed / dt_feed

    # fed rate: trainer consumes the multi-process pipeline's device feed
    # (uint8 over the wire, /255 normalize fused on device) — the worker
    # pool decodes while the device computes and the feed thread fences
    # one transfer at a time (iter_prefetcher.h:47 analogue).  Skipped
    # when the train stage failed (trainer is None): the decode/feed
    # rates above are host properties and still stand.
    loss = None
    n = 0
    t0 = time.perf_counter()
    if trainer is not None:
        fed = mx.io.ImageRecordIter(
            path_imgrec=rec_path, path_imgidx=idx_path,
            data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
            dtype=dtype, layout="NHWC" if layout == "NHWC" else "NCHW",
            device_tail=True, std_r=255.0, std_g=255.0, std_b=255.0,
            preprocess_threads=min(4, os.cpu_count() or 1),
            prefetch_buffer=2)
        for b in fed:
            if b.data[0].shape[0] != batch:
                break
            loss = trainer.step(b.data[0], b.label[0])
            n += batch
        if loss is not None:
            loss.asscalar()
        if hasattr(fed.base, "close"):
            fed.base.close()
    dt_fed = time.perf_counter() - t0
    fed_rate = n / dt_fed if n else 0.0

    # stall accounting: time per fed batch not explained by the binding
    # constraint (host feed or device compute) = repo-caused serialization
    t_fed_b = dt_fed / max(1, n // batch)
    t_feed_b = dt_feed / max(1, n_feed // batch)
    t_synth_b = batch / synth_rate if synth_rate else t_fed_b
    stall = max(0.0, t_fed_b - max(t_feed_b, t_synth_b)) / t_fed_b

    import shutil
    shutil.rmtree(tmpdir, ignore_errors=True)
    out = {
        "pipeline_decode_imgs_per_sec": round(decode_rate, 2),
        "pipeline_iter_imgs_per_sec": round(feed_rate, 2),
        "pipeline_decode_thread_scaling": scaling,
        "pipeline_host_cores": os.cpu_count(),
    }
    if trainer is not None:
        # only report the trainer-fed numbers when they were measured —
        # a fake 0.0 here would displace a carried-forward real value.
        # (pipeline_fed_imgs_per_sec itself is owned by the host-only
        # pipeline_host stage since PR 3; this one includes the device
        # step in the loop)
        out["pipeline_train_fed_imgs_per_sec"] = round(fed_rate, 2)
        out["pipeline_train_stall_pct"] = round(stall * 100, 2)
    return out


if __name__ == "__main__":
    main()
