"""North-star benchmark: ResNet-50 training throughput, img/s per chip.

Baseline (BASELINE.md / docs/faq/perf.md:214 in the reference): 298.51 img/s
on V100 fp32, bs=32 — MXNet 1.2 `train_imagenet.py`.  Prints ONE JSON line.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 298.51


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    # default 256/chip: the reference's headline number is bs=32-per-GPU,
    # but modern chips need larger batches to fill the MXU — measured on
    # one chip (bf16): bs=128 → ~2000, bs=256 → ~2300, bs=512 → ~2250
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "256"))
    # keep the per-chip metric honest: batch is per chip, and the device
    # count matches the mesh the trainer actually spans
    devices = jax.devices()
    n_dev = len(devices)
    mesh = make_mesh((n_dev,), ("data",), devices)
    global_batch = batch * n_dev

    # end-to-end bf16 training: bf16 activations/params with fp32 master
    # weights in the optimizer (multi_precision) — the TPU-native analogue of
    # the reference's fp16 path (docs/faq/perf.md fp16 rows).  BN statistics
    # stay fp32 (BatchNorm.cast).  MXTPU_BENCH_DTYPE=float32 forces full
    # precision.
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    # NHWC is the TPU-native conv layout (channels on the minor axis)
    layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")
    # MXU precision for fp32 matmuls/convs; MXTPU_BENCH_PRECISION=float32
    # (with MXTPU_BENCH_DTYPE=float32) forces a true full-precision run
    precision = os.environ.get("MXTPU_BENCH_PRECISION", "bfloat16")
    jax.config.update("jax_default_matmul_precision", precision)

    rng = np.random.RandomState(0)

    def make_batch(b):
        shape = (b, 3, 224, 224) if layout == "NCHW" else (b, 224, 224, 3)
        x = rng.rand(*shape).astype(np.float32)
        return (mx.nd.array(x).astype(dtype),
                mx.nd.array((rng.rand(b) * 1000).astype(np.int64)))

    def build_trainer():
        # rebuilt from scratch on every OOM retry: the step jit donates the
        # parameter/state buffers, so a failed step may have invalidated them
        net = vision.resnet50_v1(layout=layout)
        net.initialize(mx.init.Xavier())
        net.cast(dtype)
        return DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4,
             "multi_precision": dtype != "float32"}, mesh=mesh)

    # warmup (compile); halve the batch on OOM so the metric always prints
    while True:
        try:
            trainer = build_trainer()
            x, y = make_batch(global_batch)
            for _ in range(3):
                trainer.step(x, y).asscalar()
            break
        except Exception as e:  # RESOURCE_EXHAUSTED etc.
            if "RESOURCE_EXHAUSTED" not in str(e) or batch <= 8:
                raise
            batch //= 2
            global_batch = batch * n_dev

    iters = int(os.environ.get("MXTPU_BENCH_ITERS", "10"))
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(x, y)
    loss.asscalar()  # sync
    dt = time.perf_counter() - t0

    imgs_per_sec_per_chip = global_batch * iters / dt / n_dev

    result = {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec_per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(imgs_per_sec_per_chip / BASELINE_IMGS_PER_SEC, 3),
    }

    # -- pipeline-fed measurement (reference: train_imagenet.py feeds the
    # trainer through ImageRecordIter, src/io/iter_image_recordio_2.cc).
    # A synthetic JPEG .rec is packed on the fly; both the iterator-only
    # rate (native decode) and the trainer-fed rate are reported.  On this
    # host the decode path is CPU-bound (os.cpu_count() cores drive
    # libjpeg), so the pipeline rate is a host property, not a chip one.
    if os.environ.get("MXTPU_BENCH_PIPELINE", "1") == "1":
        try:
            result.update(_pipeline_bench(
                trainer, batch, layout, dtype,
                synth_rate=imgs_per_sec_per_chip * n_dev))
        except Exception as e:  # never lose the primary metric
            result["pipeline_error"] = str(e)[:200]

    # -- inference: bf16 denominator + int8 (reference: benchmark_score.py
    # fp32/fp16 table in docs/faq/perf.md:156,170, and quantized resnet via
    # quantize_graph_pass.cc + quantized_conv/pooling/fc kernels).
    # Each bench guards itself: one failing must not drop the other.
    run_bf16 = os.environ.get("MXTPU_BENCH_BF16", "1") == "1"
    run_int8 = os.environ.get("MXTPU_BENCH_INT8", "1") == "1"
    if run_bf16 or run_int8:
        # drop the trainer's HBM (params, fp32 masters, momentum,
        # donated activations) before binding the inference executors
        trainer = None
        import gc
        gc.collect()
    if run_bf16:
        try:
            result.update(_bf16_infer_bench())
        except Exception as e:
            result["bf16_infer_error"] = str(e)[:200]
    if run_int8:
        try:
            import gc
            gc.collect()
            result.update(_int8_bench())
        except Exception as e:
            result["int8_error"] = str(e)[:200]

    print(json.dumps(result))


def _bf16_infer_bench(batch=None, iters=20):
    """bf16 inference denominator (reference: benchmark_score.py, the fp16
    row of docs/faq/perf.md:170) — NHWC bf16 jitted forward, bs>=64."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    batch = batch or int(os.environ.get("MXTPU_BENCH_INFER_BATCH", "256"))
    rng = np.random.RandomState(0)
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    net.hybridize()
    x = mx.nd.array(rng.rand(batch, 224, 224, 3).astype(np.float32)) \
        .astype("bfloat16")
    out = net(x)
    out.asnumpy()  # compile + hard sync (device->host round-trip; the
    # axon tunnel's block_until_ready is not a reliable fence)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = net(x)
    out.asnumpy()
    dt = time.perf_counter() - t0
    return {"bf16_infer_imgs_per_sec": round(batch * iters / dt, 2)}


def _blob_images(rng, n, nclass=8, size=224):
    """Class-separable synthetic images — gives the accuracy gate a
    functioning classifier to quantize instead of argmax roulette on
    near-uniform untrained logits (shared impl: test_utils)."""
    from mxnet_tpu.test_utils import separable_images
    return separable_images(rng, n, nclass=nclass, size=size, channels=3,
                            noise=0.3, base=0.8)


def _int8_bench(batch=None, iters=20, calib_batch=64, eval_images=1024,
                train_images=2048):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.symbol.models import resnet_symbol

    batch = batch or int(os.environ.get("MXTPU_BENCH_INFER_BATCH", "256"))
    rng = np.random.RandomState(0)
    # NHWC end to end: the quantized graph keeps the TPU-native layout so
    # the int8 convs/dots land on the MXU int8 path without transposes.
    # Train briefly on separable synthetic data first: the VERDICT r2
    # accuracy gate ("int8 top-1 within 1% of fp32 on 1000+ images") needs
    # a model whose predictions mean something.
    Xtr, ytr = _blob_images(rng, train_images)
    train_it = mx.io.NDArrayIter(Xtr, ytr, 128, shuffle=True,
                                 shuffle_seed=3)
    net = resnet_symbol(50, num_classes=8, layout="NHWC")
    mod = mx.mod.Module(net)
    # adam + seeded shuffle + seeded init: short from-scratch sgd on
    # resnet-50 sat on a knife edge where run-to-run noise decided
    # whether the gate's classifier converged at all
    mx.random.seed(11)
    np.random.seed(11)
    mod.fit(train_it, num_epoch=5, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3})
    arg, aux = mod.get_params()
    calib_it = mx.io.NDArrayIter(Xtr[:calib_batch], ytr[:calib_batch],
                                 calib_batch)
    # entropy (KL) calibration + BN folding — the round-3 int8 pipeline
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=calib_it, num_calib_examples=calib_batch,
        calib_mode="entropy", excluded_sym_names=["stem_conv"])

    # fp32 eval predictions captured BEFORE the fp32 executor is dropped
    # so it never coexists with the int8 one in HBM
    Xev, yev = _blob_images(np.random.RandomState(7), eval_images)
    eval_sets = [(Xev[s:s + batch], yev[s:s + batch])
                 for s in range(0, eval_images, batch)]
    fp32_preds = []
    fp32_correct = 0
    infer_mod = mx.mod.Module(net)
    it0 = mx.io.NDArrayIter(Xev[:batch], yev[:batch], batch)
    infer_mod.bind(it0.provide_data, it0.provide_label, for_training=False)
    infer_mod.set_params(arg, aux)
    for Xe, ye in eval_sets:
        eb = mx.io.DataBatch(data=[mx.nd.array(Xe)], label=[])
        infer_mod.forward(eb, is_train=False)
        pred = infer_mod.get_outputs()[0].asnumpy().argmax(1)
        fp32_preds.append(pred)
        fp32_correct += int((pred == ye).sum())
    mod = infer_mod = None
    import gc
    gc.collect()

    Xb = rng.rand(batch, 224, 224, 3).astype(np.float32)
    it = mx.io.NDArrayIter(Xb, np.zeros(batch, np.float32), batch)
    qmod = mx.mod.Module(qsym)
    qmod.bind(it.provide_data, it.provide_label, for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    b = next(iter(it))
    qmod.forward(b, is_train=False)
    qmod.get_outputs()[0].asnumpy()  # compile + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        qmod.forward(b, is_train=False)
    qmod.get_outputs()[0].asnumpy()
    dt = time.perf_counter() - t0
    out = {"int8_infer_imgs_per_sec": round(batch * iters / dt, 2)}

    agree = tot = int8_correct = 0
    for (Xe, ye), ref in zip(eval_sets, fp32_preds):
        eb = mx.io.DataBatch(data=[mx.nd.array(Xe)], label=[])
        qmod.forward(eb, is_train=False)
        got = qmod.get_outputs()[0].asnumpy().argmax(1)
        agree += int((ref == got).sum())
        int8_correct += int((got == ye).sum())
        tot += len(got)
    out["int8_top1_agreement"] = round(agree / tot, 4)
    out["fp32_top1_acc"] = round(fp32_correct / tot, 4)
    out["int8_top1_acc"] = round(int8_correct / tot, 4)
    out["int8_top1_drop"] = round((fp32_correct - int8_correct) / tot, 4)
    return out


def _pipeline_bench(trainer, batch, layout, dtype, n_records=1024,
                    synth_rate=None):
    import io as _pyio
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    tmpdir = tempfile.mkdtemp(prefix="mxtpu_bench_rec_")
    rec_path = os.path.join(tmpdir, "synth.rec")
    idx_path = os.path.join(tmpdir, "synth.idx")
    rng = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    buf = _pyio.BytesIO()
    for i in range(n_records):
        img = rng.randint(0, 255, (224, 224, 3), np.uint8)
        buf.seek(0)
        buf.truncate()
        Image.fromarray(img).save(buf, format="JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        writer.write_idx(i, recordio.pack(header, buf.getvalue()))
    writer.close()

    # uint8 + NHWC: the decoder's own layout, so the host does zero
    # transpose/cast work and the host->device transfer is 4x narrower
    # than fp32; normalization fuses into the device program.
    # NOTE the iterator produces batches whose nd.array already *dispatches*
    # the h2d transfer; rates below differ by what they wait for:
    #   decode rate  — host decode+assembly only (no transfer fence)
    #   feed rate    — decode + transfer fenced on device (DeviceFeedIter):
    #                  the true rate at which the device can be fed
    #   fed rate     — full training consuming the device feed
    def make_it():
        return mx.io.ImageRecordIter(
            path_imgrec=rec_path, path_imgidx=idx_path,
            data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
            dtype="uint8", layout="NHWC" if layout == "NHWC" else "NCHW")

    # pure host decode rate + decode-thread scaling harness (reference:
    # preprocess_threads / the OMP decode team in
    # iter_image_recordio_2.cc:139): native libjpeg decode of the whole
    # record set, no device dispatch in the loop (an iterator-based
    # measure would include h2d transfer backpressure and measure the
    # tunnel, not the host).  On a 1-core host the thread curve is flat —
    # the harness proves the architecture.
    from mxnet_tpu import _native
    scaling = {}
    decode_rate = 0.0
    if _native.available():
        reader = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
        all_bufs = [recordio.unpack(reader.read_idx(i))[1]
                    for i in range(n_records)]
        reader.close()
        t0 = time.perf_counter()
        _native.decode_batch(all_bufs, 224, 224, 3)
        decode_rate = round(n_records / (time.perf_counter() - t0), 2)
        for nt in (1, 2, 4):
            t0 = time.perf_counter()
            _native.decode_batch(all_bufs[:batch], 224, 224, 3,
                                 num_threads=nt)
            scaling[str(nt)] = round(batch / (time.perf_counter() - t0), 2)

    prep = jax.jit(lambda x: (x.astype(jnp.float32) / 255.0).astype(dtype))
    # warm the prep jit so its compile (tens of seconds) never lands
    # inside a timed window
    import numpy as _np
    prep(jnp.asarray(_np.zeros((batch, 224, 224, 3), _np.uint8))) \
        .block_until_ready()

    # feed rate: decode + fenced device transfer, no training.  The timer
    # starts BEFORE the iterator is built: its worker begins prefetching
    # at construction, and with only ~4 batches the warm prefetch would
    # otherwise hide most of the feed work.
    t0 = time.perf_counter()
    feed = mx.io.DeviceFeedIter(make_it(), transform=prep)
    n_feed = 0
    for b in feed:
        n_feed += b.data[0].shape[0]
    dt_feed = time.perf_counter() - t0
    feed_rate = n_feed / dt_feed

    # fed rate: trainer consumes the double-buffered device feed — the
    # worker fences one transfer at a time while the previous step's
    # compute runs on device (iter_prefetcher.h:47 analogue)
    loss = None
    n = 0
    t0 = time.perf_counter()
    fed = mx.io.DeviceFeedIter(make_it(), transform=prep)
    for b in fed:
        if b.data[0].shape[0] != batch:
            break
        loss = trainer.step(b.data[0], b.label[0])
        n += batch
    if loss is not None:
        loss.asscalar()
    dt_fed = time.perf_counter() - t0
    fed_rate = n / dt_fed if n else 0.0

    # stall accounting: time per fed batch not explained by the binding
    # constraint (host feed or device compute) = repo-caused serialization
    t_fed_b = dt_fed / max(1, n // batch)
    t_feed_b = dt_feed / max(1, n_feed // batch)
    t_synth_b = batch / synth_rate if synth_rate else t_fed_b
    stall = max(0.0, t_fed_b - max(t_feed_b, t_synth_b)) / t_fed_b

    import shutil
    shutil.rmtree(tmpdir, ignore_errors=True)
    return {
        "pipeline_decode_imgs_per_sec": round(decode_rate, 2),
        "pipeline_iter_imgs_per_sec": round(feed_rate, 2),
        "pipeline_fed_imgs_per_sec": round(fed_rate, 2),
        "pipeline_stall_pct": round(stall * 100, 2),
        "pipeline_decode_thread_scaling": scaling,
        "pipeline_host_cores": os.cpu_count(),
    }


if __name__ == "__main__":
    main()
