"""North-star benchmark: ResNet-50 training throughput, img/s per chip.

Baseline (BASELINE.md / docs/faq/perf.md:214 in the reference): 298.51 img/s
on V100 fp32, bs=32 — MXNet 1.2 `train_imagenet.py`.  Prints ONE JSON line.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 298.51


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    # default 256/chip: the reference's headline number is bs=32-per-GPU,
    # but modern chips need larger batches to fill the MXU — measured on
    # one chip (bf16): bs=128 → ~2000, bs=256 → ~2300, bs=512 → ~2250
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "256"))
    # keep the per-chip metric honest: batch is per chip, and the device
    # count matches the mesh the trainer actually spans
    devices = jax.devices()
    n_dev = len(devices)
    mesh = make_mesh((n_dev,), ("data",), devices)
    global_batch = batch * n_dev

    # end-to-end bf16 training: bf16 activations/params with fp32 master
    # weights in the optimizer (multi_precision) — the TPU-native analogue of
    # the reference's fp16 path (docs/faq/perf.md fp16 rows).  BN statistics
    # stay fp32 (BatchNorm.cast).  MXTPU_BENCH_DTYPE=float32 forces full
    # precision.
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    # NHWC is the TPU-native conv layout (channels on the minor axis)
    layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")
    # MXU precision for fp32 matmuls/convs; MXTPU_BENCH_PRECISION=float32
    # (with MXTPU_BENCH_DTYPE=float32) forces a true full-precision run
    precision = os.environ.get("MXTPU_BENCH_PRECISION", "bfloat16")
    jax.config.update("jax_default_matmul_precision", precision)

    rng = np.random.RandomState(0)

    def make_batch(b):
        shape = (b, 3, 224, 224) if layout == "NCHW" else (b, 224, 224, 3)
        x = rng.rand(*shape).astype(np.float32)
        return (mx.nd.array(x).astype(dtype),
                mx.nd.array((rng.rand(b) * 1000).astype(np.int64)))

    def build_trainer():
        # rebuilt from scratch on every OOM retry: the step jit donates the
        # parameter/state buffers, so a failed step may have invalidated them
        net = vision.resnet50_v1(layout=layout)
        net.initialize(mx.init.Xavier())
        net.cast(dtype)
        return DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4,
             "multi_precision": dtype != "float32"}, mesh=mesh)

    # warmup (compile); halve the batch on OOM so the metric always prints
    while True:
        try:
            trainer = build_trainer()
            x, y = make_batch(global_batch)
            for _ in range(3):
                trainer.step(x, y).asscalar()
            break
        except Exception as e:  # RESOURCE_EXHAUSTED etc.
            if "RESOURCE_EXHAUSTED" not in str(e) or batch <= 8:
                raise
            batch //= 2
            global_batch = batch * n_dev

    iters = int(os.environ.get("MXTPU_BENCH_ITERS", "10"))
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(x, y)
    loss.asscalar()  # sync
    dt = time.perf_counter() - t0

    imgs_per_sec_per_chip = global_batch * iters / dt / n_dev
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec_per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(imgs_per_sec_per_chip / BASELINE_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
