// Native I/O runtime: recordio scan + multithreaded JPEG decode/resize.
//
// Reference equivalents: dmlc-core recordio (src/io/ in the reference uses
// dmlc::RecordIOReader) and the OMP JPEG decode loop of
// ImageRecordIOParser2 (src/io/iter_image_recordio_2.cc:139) — the hot
// host path feeding the accelerator.  Python binds via ctypes
// (mxnet_tpu/_native.py); everything is plain C ABI.
//
// Build: g++ -O3 -march=native -shared -fPIC -std=c++17 mxtpu_io.cc \
//        -o libmxtpu_io.so -ljpeg -lpthread

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode JPEG from memory into RGB (or grayscale) HWC uint8.
// Returns 0 on success; fills *w/*h/*c.  Caller owns `out` (resized here).
int decode_jpeg(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                int* w, int* h, int* c, int want_channels) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = want_channels == 1 ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  *c = cinfo.output_components;
  out->resize(static_cast<size_t>(*w) * *h * *c);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * *w * *c;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Bilinear resize HWC uint8.
void resize_bilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                     int dh, int dw) {
  const float ys = static_cast<float>(sh) / dh;
  const float xs = static_cast<float>(sw) / dw;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * ys - 0.5f;
    int y0 = fy < 0 ? 0 : static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * xs - 0.5f;
      int x0 = fx < 0 ? 0 : static_cast<int>(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int k = 0; k < c; ++k) {
        float v00 = src[(y0 * sw + x0) * c + k];
        float v01 = src[(y0 * sw + x1) * c + k];
        float v10 = src[(y1 * sw + x0) * c + k];
        float v11 = src[(y1 * sw + x1) * c + k];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * c + k] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Scan a recordio file, writing record byte offsets into `offsets`
// (capacity `max_n`).  Returns the number of records, or -1 on error.
long mxtpu_recordio_index(const char* path, long* offsets, long max_n) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  long n = 0;
  for (;;) {
    long pos = std::ftell(f);
    uint32_t head[2];
    if (std::fread(head, 4, 2, f) != 2) break;
    if (head[0] != kMagic) {
      std::fclose(f);
      return -1;
    }
    // dmlc continuation records (cflag != 0) split one logical record
    // across parts when the payload contains the magic word; refuse them
    // rather than mis-index (the Python reader then owns the file)
    if ((head[1] >> 29) != 0) {
      std::fclose(f);
      return -1;
    }
    uint32_t len = head[1] & kLenMask;
    uint32_t pad = (4 - len % 4) % 4;
    if (n < max_n && offsets) offsets[n] = pos;
    ++n;
    if (std::fseek(f, len + pad, SEEK_CUR) != 0) break;
  }
  std::fclose(f);
  return n;
}

// Read one record payload at `offset` into `out` (capacity `cap`).
// Returns payload length or -1.
long mxtpu_recordio_read(const char* path, long offset, uint8_t* out,
                         long cap) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, offset, SEEK_SET) != 0) {
    std::fclose(f);
    return -1;
  }
  uint32_t head[2];
  if (std::fread(head, 4, 2, f) != 2 || head[0] != kMagic ||
      (head[1] >> 29) != 0) {
    std::fclose(f);
    return -1;
  }
  long len = head[1] & kLenMask;
  if (len > cap) {
    std::fclose(f);
    return -1;
  }
  long got = static_cast<long>(std::fread(out, 1, len, f));
  std::fclose(f);
  return got == len ? len : -1;
}

// Decode a batch of JPEG buffers in parallel into one contiguous
// (n, out_h, out_w, channels) uint8 HWC tensor.  Each image is
// short-side-resized to `resize_short` (if > 0) then center-cropped to
// (out_h, out_w).  Returns number of failures (0 = all good).
long mxtpu_decode_batch(const uint8_t** bufs, const long* lens, long n,
                        uint8_t* out, int out_h, int out_w, int channels,
                        int resize_short, int num_threads) {
  std::atomic<long> next(0), failures(0);
  const size_t img_stride =
      static_cast<size_t>(out_h) * out_w * channels;
  auto worker = [&]() {
    std::vector<uint8_t> raw, resized;
    for (;;) {
      long i = next.fetch_add(1);
      if (i >= n) return;
      int w = 0, h = 0, c = 0;
      if (decode_jpeg(bufs[i], lens[i], &raw, &w, &h, &c, channels) != 0 ||
          c != channels) {
        failures.fetch_add(1);
        std::memset(out + i * img_stride, 0, img_stride);
        continue;
      }
      const uint8_t* src = raw.data();
      int sw = w, sh = h;
      if (resize_short > 0) {
        int nw, nh;
        if (h < w) {
          nh = resize_short;
          nw = static_cast<int>(static_cast<float>(w) * resize_short / h);
        } else {
          nw = resize_short;
          nh = static_cast<int>(static_cast<float>(h) * resize_short / w);
        }
        resized.resize(static_cast<size_t>(nw) * nh * c);
        resize_bilinear(raw.data(), h, w, c, resized.data(), nh, nw);
        src = resized.data();
        sw = nw;
        sh = nh;
      }
      // center-crop; images smaller than the target follow the python
      // center_crop semantics (image.py scale_down): shrink the crop
      // window to fit at the target aspect, crop the center, then resize
      // the crop up to the target — not a full-image stretch
      if (sh < out_h || sw < out_w) {
        float cw = static_cast<float>(out_w), ch = static_cast<float>(out_h);
        if (sh < ch) {
          cw = cw * sh / ch;
          ch = static_cast<float>(sh);
        }
        if (sw < cw) {
          ch = ch * sw / cw;
          cw = static_cast<float>(sw);
        }
        int icw = static_cast<int>(cw), ich = static_cast<int>(ch);
        if (icw < 1) icw = 1;
        if (ich < 1) ich = 1;
        int y0 = (sh - ich) / 2;
        int x0 = (sw - icw) / 2;
        std::vector<uint8_t> crop(static_cast<size_t>(ich) * icw * c);
        for (int y = 0; y < ich; ++y) {
          std::memcpy(crop.data() + static_cast<size_t>(y) * icw * c,
                      src + (static_cast<size_t>(y0 + y) * sw + x0) * c,
                      static_cast<size_t>(icw) * c);
        }
        std::vector<uint8_t> tmp(static_cast<size_t>(out_h) * out_w * c);
        resize_bilinear(crop.data(), ich, icw, c, tmp.data(), out_h, out_w);
        std::memcpy(out + i * img_stride, tmp.data(), img_stride);
      } else {
        int y0 = (sh - out_h) / 2;
        int x0 = (sw - out_w) / 2;
        for (int y = 0; y < out_h; ++y) {
          std::memcpy(out + i * img_stride +
                          static_cast<size_t>(y) * out_w * c,
                      src + (static_cast<size_t>(y0 + y) * sw + x0) * c,
                      static_cast<size_t>(out_w) * c);
        }
      }
    }
  };
  int nt = num_threads > 0 ? num_threads : 1;
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  return failures.load();
}

int mxtpu_version() { return 1; }

}  // extern "C"
