"""Monitor: per-op output statistics during training.

Reference: ``python/mxnet/monitor.py`` — taps every op output via
MXExecutorSetMonitorCallback (c_api.h:1720).  TPU-native: a monitored
module evaluates the symbol's *internals* group on demand (one extra jitted
program that returns every intermediate) — no executor hook needed, and
XLA dead-code-eliminates it when not installed.

Lazy by construction (the SRC004 discipline): ``observe`` and the eager
tap *park* device-resident outputs; the ``stat_func`` (and its implied
device→host sync) runs only at :meth:`toc`/:meth:`toc_print` — the same
interval boundary the reference prints at — so a monitored training loop
never blocks the engine's run-ahead window once per batch.  A bounded
pending queue (``MXTPU_MONITOR_MAX_PENDING``) force-drains the oldest
entries if ``toc`` never comes.  Queue depth and drain cost register
into the telemetry metrics registry (``mxtpu_monitor_*``).
"""
from __future__ import annotations

import logging
import os
import re
import time

import numpy as _np

from .ndarray import NDArray

# parked per-op outputs beyond this force-drain eagerly (a tic() without
# toc() must not pin unbounded device memory)
_MAX_PENDING = int(os.environ.get("MXTPU_MONITOR_MAX_PENDING", "1024"))


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return _np.abs(x).mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []          # computed (step, name, stat) triples
        self._pending = []       # parked (step, name, device value)
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        # drain accounting, scraped through the registry (weakly held —
        # a dropped monitor leaves the scrape)
        self._observed = 0
        self._drains = 0
        self._drain_s = 0.0
        from . import telemetry as _tele
        _tele.registry().register_collector(self._metrics_samples,
                                            name="monitor")

    def _metrics_samples(self):
        return {
            "mxtpu_monitor_pending": len(self._pending),
            "mxtpu_monitor_observed_total": self._observed,
            "mxtpu_monitor_drains_total": self._drains,
            "mxtpu_monitor_drain_seconds_total": round(self._drain_s, 6),
        }

    def _park(self, step, name, value):
        """Queue a device value WITHOUT fetching it; the stat (and its
        host sync) waits for the toc boundary."""
        self._pending.append((step, name, value))
        self._observed += 1
        if len(self._pending) > _MAX_PENDING:
            # bound device memory: force-drain the oldest half eagerly
            overflow, self._pending = (
                self._pending[:_MAX_PENDING // 2],
                self._pending[_MAX_PENDING // 2:])
            self._drain(overflow)

    def _drain(self, entries):
        t0 = time.perf_counter()
        for step, name, value in entries:
            self.queue.append((step, name,
                               self.stat_func(_np.asarray(value))))
        self._drains += 1
        self._drain_s += time.perf_counter() - t0

    def install(self, module):
        """Attach to a module (reference installs a C callback on the
        executor; here the module calls ``observe`` after each forward)."""
        self.exes.append(module)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self._pending = []
            self.activated = True
        self.step += 1

    def observe(self, module):
        if not self.activated:
            return
        exe = module._exec
        internals = module._symbol.get_internals()
        names = internals.list_outputs()
        from .symbol.symbol import make_graph_fn
        from . import _rng
        import jax
        fn = jax.jit(make_graph_fn(internals, train=False))
        arg_vals = {n: a._data for n, a in exe.arg_dict.items()}
        aux_vals = {n: a._data for n, a in exe.aux_dict.items()}
        outs, _ = fn(arg_vals, aux_vals, _rng.next_key())
        for name, value in zip(names, outs):
            if self.re_prog.match(name):
                # parked lazily: outs are future-backed device arrays;
                # the stat computes at toc, not here
                self._park(self.step, name, value)

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        pending, self._pending = self._pending, []
        self._drain(pending)
        res = [(n, k, str(v)) for n, k, v in self.queue]
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)

    # -- eager per-op tap ---------------------------------------------------
    def install_eager(self):
        """Tap every imperative op execution (the eager-mode analogue of
        MXExecutorSetMonitorCallback, c_api.h:1720): each nd.* invoke
        parks its named outputs while activated (stats computed at
        toc)."""
        from .ndarray import ndarray as _ndmod

        def tap(op_name, outs):
            if not self.activated:
                return
            for i, o in enumerate(outs):
                name = "%s_output%s" % (op_name, i if len(outs) > 1 else "")
                if self.re_prog.match(name):
                    self._park(self.step, name, o._data)

        self._eager_tap = tap
        _ndmod._MONITOR_TAPS.append(tap)
        return self

    def uninstall_eager(self):
        from .ndarray import ndarray as _ndmod
        tap = getattr(self, "_eager_tap", None)
        if tap is not None and tap in _ndmod._MONITOR_TAPS:
            _ndmod._MONITOR_TAPS.remove(tap)
        self._eager_tap = None
