"""Monitor: per-op output statistics during training.

Reference: ``python/mxnet/monitor.py`` — taps every op output via
MXExecutorSetMonitorCallback (c_api.h:1720).  TPU-native: a monitored
module evaluates the symbol's *internals* group on demand (one extra jitted
program that returns every intermediate) — no executor hook needed, and
XLA dead-code-eliminates it when not installed.
"""
from __future__ import annotations

import logging
import re

import numpy as _np

from .ndarray import NDArray


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return _np.abs(x).mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, module):
        """Attach to a module (reference installs a C callback on the
        executor; here the module calls ``observe`` after each forward)."""
        self.exes.append(module)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def observe(self, module):
        if not self.activated:
            return
        exe = module._exec
        internals = module._symbol.get_internals()
        names = internals.list_outputs()
        from .symbol.symbol import make_graph_fn
        from . import _rng
        import jax
        fn = jax.jit(make_graph_fn(internals, train=False))
        arg_vals = {n: a._data for n, a in exe.arg_dict.items()}
        aux_vals = {n: a._data for n, a in exe.aux_dict.items()}
        outs, _ = fn(arg_vals, aux_vals, _rng.next_key())
        for name, value in zip(names, outs):
            if self.re_prog.match(name):
                self.queue.append((self.step, name,
                                   self.stat_func(_np.asarray(value))))

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = [(n, k, str(v)) for n, k, v in self.queue]
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)

    # -- eager per-op tap ---------------------------------------------------
    def install_eager(self):
        """Tap every imperative op execution (the eager-mode analogue of
        MXExecutorSetMonitorCallback, c_api.h:1720): each nd.* invoke
        reports its named outputs while activated."""
        from .ndarray import ndarray as _ndmod

        def tap(op_name, outs):
            if not self.activated:
                return
            for i, o in enumerate(outs):
                name = "%s_output%s" % (op_name, i if len(outs) > 1 else "")
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(_np.asarray(o._data))))

        self._eager_tap = tap
        _ndmod._MONITOR_TAPS.append(tap)
        return self

    def uninstall_eager(self):
        from .ndarray import ndarray as _ndmod
        tap = getattr(self, "_eager_tap", None)
        if tap is not None and tap in _ndmod._MONITOR_TAPS:
            _ndmod._MONITOR_TAPS.remove(tap)
        self._eager_tap = None
