"""`mx.name` (reference: python/mxnet/name.py) — NameManager assigning
default names to symbols, plus a Prefix variant."""
from .symbol.symbol import NameManager as _BaseNameManager

__all__ = ["NameManager", "Prefix"]


class NameManager(_BaseNameManager):
    """Context-manager name scope with fresh counters
    (reference: name.py NameManager — `with NameManager():` resets the
    default-naming counters within the scope)."""

    def __enter__(self):
        self._old = _BaseNameManager._current
        _BaseNameManager._current = self
        return self

    def __exit__(self, *exc):
        _BaseNameManager._current = self._old


class Prefix(NameManager):
    """Prepend a prefix to all names created in scope
    (reference: name.py Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
