"""Base utilities: error type, dtype handling, registries, env-var config.

TPU-native rebuild of the reference's base layer. The reference funnels
everything through a 187-function C ABI (``include/mxnet/c_api.h``) with string
kwargs and a dmlc parameter registry; here the frontend is pure Python over JAX,
so "base" reduces to dtype plumbing, a typed env config (reference:
``docs/faq/env_var.md``, ~40 MXNET_* vars read via dmlc::GetEnv), and the
generic registry used for optimizers/metrics/initializers (reference:
``python/mxnet/registry.py``).
"""
from __future__ import annotations

import os
import numpy as np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: python/mxnet/base.py:83)."""


string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

# ---------------------------------------------------------------------------
# dtype handling.  The reference maps numpy dtypes to int codes across the C
# ABI (python/mxnet/base.py _DTYPE_NP_TO_MX).  We keep the same public names
# and codes for serialization parity, backed by numpy/jax dtypes.
# ---------------------------------------------------------------------------
_DTYPE_NP_TO_MX = {
    None: -1,
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
}
_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}

try:  # bfloat16 is first-class on TPU; the reference has no such type.
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
    _DTYPE_NP_TO_MX[bfloat16] = 7
    _DTYPE_MX_TO_NP[7] = bfloat16
except ImportError:  # pragma: no cover
    bfloat16 = None


def mx_dtype_code(dtype) -> int:
    return _DTYPE_NP_TO_MX[np.dtype(dtype) if dtype is not None else None]


def np_dtype(code_or_dtype):
    if isinstance(code_or_dtype, int):
        return _DTYPE_MX_TO_NP[code_or_dtype]
    return np.dtype(code_or_dtype)


# ---------------------------------------------------------------------------
# Typed env config — replaces scattered dmlc::GetEnv reads.  Names keep the
# MXNET_ prefix so reference run scripts keep working.
# ---------------------------------------------------------------------------
class _Config:
    """Typed view over MXNET_* environment variables.

    Reference reads these lazily at point of use (e.g.
    src/storage/pooled_storage_manager.h:57, src/engine/engine.cc:32);
    we centralize them.  Unknown vars are ignored.
    """

    _SPECS = {
        # name -> (type, default)
        "MXNET_ENGINE_TYPE": (str, "XLA"),  # informational; XLA schedules ops
        "MXNET_EXEC_BULK_EXEC_TRAIN": (int, 1),
        "MXNET_EXEC_BULK_EXEC_INFERENCE": (int, 1),
        "MXNET_KVSTORE_BIGARRAY_BOUND": (int, 1000000),
        "MXNET_ENABLE_GPU_P2P": (int, 1),
        "MXNET_PROFILER_AUTOSTART": (int, 0),
        "MXNET_PROFILER_MODE": (int, 0),
        "MXNET_BACKWARD_DO_MIRROR": (int, 0),  # maps to jax.checkpoint policy
        "MXNET_CPU_WORKER_NTHREADS": (int, 1),
        "MXNET_DEFAULT_DTYPE": (str, "float32"),
        "MXNET_SAFE_ACCUMULATION": (int, 1),
    }

    def get(self, name, default=None):
        spec = self._SPECS.get(name)
        raw = os.environ.get(name)
        if raw is None:
            return spec[1] if spec else default
        typ = spec[0] if spec else (type(default) if default is not None else str)
        try:
            return typ(raw)
        except (TypeError, ValueError):
            return spec[1] if spec else default

    def __getattr__(self, name):
        if name.startswith("MXNET_"):
            return self.get(name)
        raise AttributeError(name)


config = _Config()


# ---------------------------------------------------------------------------
# Generic object registry (reference: python/mxnet/registry.py) used by
# optimizer/metric/initializer subsystems.
# ---------------------------------------------------------------------------
class Registry:
    def __init__(self, nickname):
        self._nickname = nickname
        self._registry = {}

    def register(self, klass, name=None):
        name = (name or klass.__name__).lower()
        self._registry[name] = klass
        return klass

    def alias(self, klass, *names):
        for n in names:
            self._registry[n.lower()] = klass
        return klass

    def create(self, name, *args, **kwargs):
        if callable(name) and not isinstance(name, str):
            return name
        key = name.lower()
        if key not in self._registry:
            raise MXNetError(
                "Cannot find %s %r. Registered: %s"
                % (self._nickname, name, sorted(self._registry))
            )
        return self._registry[key](*args, **kwargs)

    def find(self, name):
        return self._registry[name.lower()]

    def __contains__(self, name):
        return name.lower() in self._registry

    def keys(self):
        return sorted(self._registry)
