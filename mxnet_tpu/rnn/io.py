"""Bucketing data iterator for variable-length sequences.

Reference: ``python/mxnet/rnn/io.py`` — BucketSentenceIter assigns each
sentence to the smallest bucket that fits, pads within the bucket, and
emits batches tagged with ``bucket_key`` for BucketingModule.  On TPU each
bucket is one jit specialization; bucketing bounds the number of
recompiles (SURVEY.md §5 long-context: bucketing + scan + remat).
"""
from __future__ import annotations

import bisect
import random

import numpy as _np

from .. import ndarray as nd
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            lengths = [len(s) for s in sentences]
            max_len = max(lengths)
            counts = _np.bincount(lengths, minlength=max_len + 1)
            buckets = [i for i, j in enumerate(counts) if j >= batch_size]
            if not buckets:
                buckets = [max_len]
        buckets.sort()
        self.buckets = buckets
        self.data = [[] for _ in buckets]
        self.invalid_label = invalid_label

        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                continue
            buff = _np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        # empty buckets become (0, bucket_len) so the label shift in reset()
        # stays 2-D
        self.data = [_np.asarray(i, dtype=dtype) if i else
                     _np.zeros((0, buckets[k]), dtype=dtype)
                     for k, i in enumerate(self.data)]

        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)

        shape = (batch_size, self.default_bucket_key) if self.major_axis == 0 \
            else (self.default_bucket_key, batch_size)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1, batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            _np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            # language-model convention: label is data shifted left by one
            label = _np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.major_axis == 1:
            data = data.T
            label = label.T
        shape = data.shape
        return DataBatch([nd.array(data)], [nd.array(label)], pad=0,
                         bucket_key=self.buckets[i],
                         provide_data=[DataDesc(self.data_name, shape,
                                                layout=self.layout)],
                         provide_label=[DataDesc(self.label_name, shape,
                                                 layout=self.layout)])
