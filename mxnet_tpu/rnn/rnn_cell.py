"""Legacy symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py).

These compose `mx.sym` graphs — used with BucketingModule for
variable-length sequence training (reference speech/rnn examples).
"""
from __future__ import annotations

from .. import initializer as init_mod
from .. import symbol
from ..symbol.symbol import Symbol

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell"]


class RNNParams:
    """Container for cell parameter symbols, shared by name
    (reference: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


def _resolve_deferred_states(states, ref, batch_axis=0):
    """Rewrite unknown-batch zeros states in place to derive their batch dim
    from ``ref`` (see ops/init.py _state_zeros_like).  The reference's nnvm
    fixpoint infers these backward; we anchor them forward instead."""
    from ..ops import registry as _reg
    for s in states:
        node = s._outputs[0][0]
        if node.op in ("_zeros", "_full"):
            shape = _reg.canonicalize(node.attrs.get("shape", "()"))
            if shape and 0 in tuple(shape):
                node.op = "_state_zeros_like"
                node.inputs = [ref._outputs[0]]
                node.attrs = {"shape": str(tuple(shape)),
                              "batch_axis": str(int(batch_axis))}
    return states


class BaseRNNCell:
    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if info is not None:
                info = dict(info)
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name="%sbegin_state_%d" % (self._prefix,
                                                    self._init_counter),
                         **info)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split fused parameter blobs into per-gate entries
        (reference: rnn_cell.py unpack_weights)."""
        args = dict(args)
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        for group_name in ["i2h", "h2h"]:
            ws = [args.pop("%s%s%s_weight" % (self._prefix, group_name, gate))
                  for gate in self._gate_names]
            bs = [args.pop("%s%s%s_bias" % (self._prefix, group_name, gate))
                  for gate in self._gate_names]
            args["%s%s_weight" % (self._prefix, group_name)] = \
                nd.concatenate(ws, axis=0)
            args["%s%s_bias" % (self._prefix, group_name)] = \
                nd.concatenate(bs, axis=0)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll into a symbol graph (reference: rnn_cell.py unroll)."""
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, Symbol):
            if length == 1:
                inputs = [symbol.squeeze(inputs, axis=axis)]
            else:
                inputs = list(symbol.split(inputs, num_outputs=length,
                                           axis=axis, squeeze_axis=1))
        if begin_state is None:
            begin_state = _resolve_deferred_states(self.begin_state(),
                                                   inputs[0], batch_axis=0)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs is None or merge_outputs:
            outputs = [symbol.expand_dims(o, axis=axis) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(states[0], self._hW, self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(states[0], self._hW, self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4, axis=-1,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh")
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(prev_h, self._hW, self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h_o = symbol.SliceChannel(
            i2h, num_outputs=3, axis=-1, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h_o = symbol.SliceChannel(
            h2h, num_outputs=3, axis=-1, name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h_o + reset_gate * h2h_o,
                                       act_type="tanh")
        next_h = update_gate * prev_h + (1.0 - update_gate) * next_h_tmp
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer cell backed by the RNN op
    (reference: rnn_cell.py FusedRNNCell over the cuDNN op)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        # flat cuDNN-layout parameter vector: 1-D, so route init through the
        # FusedRNN initializer (Xavier would reject a 1-D weight)
        self._parameter = self.params.get(
            "parameters", init=init_mod.FusedRNN(
                None, num_hidden, num_layers, mode, bidirectional, forget_bias))
        self._directions = ["l", "r"] if bidirectional else ["l"]

    @property
    def state_info(self):
        b = self._num_layers * len(self._directions)
        n = (self._mode == "lstm") + 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            inputs = [symbol.expand_dims(i, axis=0) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=0)
            axis = 0
        elif axis == 1:
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = _resolve_deferred_states(self.begin_state(), inputs,
                                                   batch_axis=1)
        states = begin_state
        rnn = symbol.RNN(inputs, self._parameter, *states,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers, mode=self._mode,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         name="%srnn" % self._prefix)
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is not None and not merge_outputs:
            outputs = list(symbol.split(outputs, num_outputs=length,
                                        axis=axis, squeeze_axis=1))
        return outputs, states

    def _slice_weights(self, arr, li, lh):
        """Yield (name, ndarray) per layer/direction in cuDNN order
        (reference: rnn_cell.py FusedRNNCell._slice_weights)."""
        import numpy as _np
        args = {}
        g = self._num_gates
        h = self._num_hidden
        d = len(self._directions)
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else _np.asarray(arr)
        off = 0
        for layer in range(self._num_layers):
            in_size = li if layer == 0 else lh * d
            for direction in self._directions:
                name = "%s%s%d" % (self._prefix, direction, layer)
                args["%s_i2h_weight" % name] = a[off:off + g * h * in_size] \
                    .reshape(g * h, in_size)
                off += g * h * in_size
                args["%s_h2h_weight" % name] = a[off:off + g * h * h] \
                    .reshape(g * h, h)
                off += g * h * h
        for layer in range(self._num_layers):
            for direction in self._directions:
                name = "%s%s%d" % (self._prefix, direction, layer)
                args["%s_i2h_bias" % name] = a[off:off + g * h]
                off += g * h
                args["%s_h2h_bias" % name] = a[off:off + g * h]
                off += g * h
        return args

    def unpack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        arr = args.pop("%sparameters" % self._prefix)
        h = self._num_hidden
        d = len(self._directions)
        g = self._num_gates
        # input size from total parameter count: total =
        #   d*g*h*(li+h) + d*2*g*h                       (layer 0)
        # + (L-1)*d*(g*h*(h*d+h) + 2*g*h)                (layers 1..L-1)
        total = arr.size if hasattr(arr, "size") else arr.shape[0]
        rest = total - (self._num_layers - 1) * d * (
            g * h * (h * d + h) + 2 * g * h) - d * 2 * g * h
        li = rest // (d * g * h) - h
        for k, v in self._slice_weights(arr, li, h).items():
            args[k] = nd.array(v)
        return args

    def pack_weights(self, args):
        import numpy as _np
        from .. import ndarray as nd
        args = dict(args)
        g = self._num_gates
        ws, bs = [], []
        for layer in range(self._num_layers):
            for direction in self._directions:
                name = "%s%s%d" % (self._prefix, direction, layer)
                ws.append(args.pop("%s_i2h_weight" % name).asnumpy().ravel())
                ws.append(args.pop("%s_h2h_weight" % name).asnumpy().ravel())
        for layer in range(self._num_layers):
            for direction in self._directions:
                name = "%s%s%d" % (self._prefix, direction, layer)
                bs.append(args.pop("%s_i2h_bias" % name).asnumpy().ravel())
                bs.append(args.pop("%s_h2h_bias" % name).asnumpy().ravel())
        args["%sparameters" % self._prefix] = nd.array(
            _np.concatenate(ws + bs))
        return args

    def unfuse(self):
        """Equivalent stack of unfused cells (reference: FusedRNNCell.unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, "relu", p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, "tanh", p),
            "lstm": lambda p: LSTMCell(self._num_hidden, p),
            "gru": lambda p: GRUCell(self._num_hidden, p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            cell._own_params = False
            cell._params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    def __init__(self, base_cell):
        base_cell._modified = True
        super().__init__()
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like:
                symbol.Dropout(symbol.ones_like(like), p=p))
        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = symbol.where(mask(self.zoneout_outputs, next_output),
                              next_output, prev_output) \
            if self.zoneout_outputs > 0 else next_output
        states = [symbol.where(mask(self.zoneout_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if self.zoneout_states > 0 else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        if isinstance(outputs, (list, tuple)):
            if not isinstance(inputs, (list, tuple)):
                axis = layout.find("T")
                inputs = list(symbol.split(inputs, num_outputs=length,
                                           axis=axis, squeeze_axis=1))
            outputs = [symbol.elemwise_add(o, i)
                       for o, i in zip(outputs, inputs)]
        else:
            if isinstance(inputs, (list, tuple)):
                axis = layout.find("T")
                inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
                inputs = symbol.Concat(*inputs, dim=axis)
            outputs = symbol.elemwise_add(outputs, inputs)
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        self._cells = [l_cell, r_cell]
        for cell in self._cells:
            self.params._params.update(cell.params._params)

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped; use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, Symbol):
            if length == 1:
                inputs = [symbol.squeeze(inputs, axis=axis)]
            else:
                inputs = list(symbol.split(inputs, num_outputs=length,
                                           axis=axis, squeeze_axis=1))
        if begin_state is None:
            begin_state = _resolve_deferred_states(self.begin_state(),
                                                   inputs[0], batch_axis=0)
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        outputs = [symbol.Concat(l, r, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l, r) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs is None or merge_outputs:
            outputs = [symbol.expand_dims(o, axis=axis) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, l_states + r_states
