"""Executor: a bound, compiled symbol.

Reference: ``include/mxnet/executor.h:53-152`` / ``src/executor/
graph_executor.cc:514`` (GraphExecutor::Init builds the full fwd+bwd nnvm
graph, infers shapes, plans memory, attaches engine ops) and the Python
wrapper ``python/mxnet/executor.py``.

TPU-native design: binding builds a pure jax function over the DAG
(``symbol.make_graph_fn``) and hands it to ``jax.jit`` — XLA is the memory
planner, op fuser and scheduler.  ``backward`` compiles the ``jax.vjp`` of
the same function (the ``nnvm::pass::Gradient`` analogue); the forward is
rematerialized inside the backward program, which XLA CSEs/schedules for
HBM reuse — the TPU equivalent of the reference's memory-sharing passes.

Data parallelism: pass ``ctx`` as a device list — the executor builds a
``Mesh`` over it, shards the data arguments on the batch axis and
replicates parameters; GSPMD inserts the gradient ``psum`` over ICI
(replacing DataParallelExecutorGroup + KVStore 'device',
``python/mxnet/module/executor_group.py:143``).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .base import MXNetError, np_dtype
from .context import Context, current_context
from .ndarray import NDArray
from .symbol.symbol import make_graph_fn

__all__ = ["Executor"]


def _fit_spec(spec, shape, mesh):
    """Best-effort fit of a group PartitionSpec onto a tensor: keep an axis
    assignment only where the dimension divides evenly (GSPMD-style; one
    group covers tensors of many ranks, as ctx_group did placement-wise)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    for d, ax in enumerate(tuple(spec)[:len(shape)]):
        if ax is None:
            out.append(None)
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        total = 1
        for a in axes:
            total *= sizes.get(a, 1)
        out.append(ax if shape[d] % total == 0 else None)
    return PartitionSpec(*out)


def _as_device_list(ctx):
    if ctx is None:
        ctx = current_context()
    if isinstance(ctx, Mesh):
        return list(ctx.devices.flat)
    if isinstance(ctx, Context):
        return [ctx.jax_device()]
    if isinstance(ctx, (list, tuple)):
        return [c.jax_device() if isinstance(c, Context) else c for c in ctx]
    return [ctx]


class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, data_names=None,
                 group2ctx=None):
        self._symbol = symbol
        self._devices = _as_device_list(ctx)
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = list(data_names) if data_names else []

        # ---- argument arrays -------------------------------------------
        if args is None:
            raise MXNetError("bind requires args")
        if isinstance(args, dict):
            arg_dict = dict(args)
        else:
            arg_dict = dict(zip(self._arg_names, args))
        missing = [n for n in self._arg_names if n not in arg_dict]
        if missing:
            raise MXNetError("missing arguments: %r" % (missing,))
        self.arg_dict = {n: _as_nd(arg_dict[n]) for n in self._arg_names}
        self.arg_arrays = [self.arg_dict[n] for n in self._arg_names]

        # ---- aux arrays -------------------------------------------------
        if aux_states is None:
            aux_states = {}
        if not isinstance(aux_states, dict):
            aux_states = dict(zip(self._aux_names, aux_states))
        self.aux_dict = {n: _as_nd(aux_states[n]) for n in self._aux_names}
        self.aux_arrays = [self.aux_dict[n] for n in self._aux_names]

        # ---- grad arrays / grad_req ------------------------------------
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in self._arg_names}
        if args_grad is None:
            args_grad = {n: NDArray(jnp.zeros_like(self.arg_dict[n]._data))
                         for n in self._arg_names
                         if self._grad_req.get(n, "null") != "null"}
        elif not isinstance(args_grad, dict):
            args_grad = dict(zip(self._arg_names, args_grad))
        self.grad_dict = {n: _as_nd(g) for n, g in args_grad.items()
                          if g is not None and self._grad_req.get(n) != "null"}
        self.grad_arrays = [self.grad_dict.get(n) for n in self._arg_names]

        self._wrt = [n for n in self._arg_names
                     if self._grad_req.get(n, "null") != "null"]

        # ---- sharding across the device mesh ---------------------------
        self._mesh = None
        if isinstance(ctx, Mesh):
            self._mesh = ctx
        elif len(self._devices) > 1:
            self._mesh = Mesh(_np.asarray(self._devices), ("data",))

        # group2ctx consumption (reference: the PlaceDevice pass turns
        # ctx_group attrs into placement, graph_executor.cc:408; here the
        # groups map to PartitionSpecs and GSPMD plans the collectives):
        # {group: PartitionSpec} shards every node/arg tagged with that
        # ctx_group.  Context values (reference API) mean replicated.
        self._group_specs = {}
        if group2ctx:
            for g, spec in group2ctx.items():
                if isinstance(spec, PartitionSpec):
                    self._group_specs[g] = spec
                elif isinstance(spec, (tuple, list)):
                    self._group_specs[g] = PartitionSpec(*spec)
                else:  # Context — placement only, replicate
                    self._group_specs[g] = PartitionSpec()
        self._arg_groups = {}
        sharding_map = None
        if self._group_specs and self._mesh is not None:
            sharding_map = {}
            for node in symbol._nodes():
                g = node.attrs.get("ctx_group")
                if g is None or g not in self._group_specs:
                    continue
                if node.op is None:
                    self._arg_groups[node.name] = g
                else:
                    # fitted per-output at trace time (shapes unknown here)
                    sharding_map[node.name] = (self._mesh,
                                               self._group_specs[g])
        self._place_arrays()

        # ---- compiled programs -----------------------------------------
        self._graph_infer = make_graph_fn(symbol, train=False,
                                          sharding_map=sharding_map)
        self._graph_train = make_graph_fn(symbol, train=True,
                                          sharding_map=sharding_map)
        self._jit_infer = jax.jit(self._graph_infer)
        self._jit_train = jax.jit(self._graph_train)

        def _bwd(arg_vals, aux_vals, head_grads, rng_key):
            fixed = {n: v for n, v in arg_vals.items() if n not in self._wrt}

            def f(wrt_vals):
                ad = dict(fixed)
                ad.update(wrt_vals)
                outs, new_aux = self._graph_train(ad, aux_vals, rng_key)
                return outs, new_aux

            (outs, new_aux), vjp = jax.vjp(
                f, {n: arg_vals[n] for n in self._wrt}, has_aux=False)
            grads = vjp((head_grads, jax.tree_util.tree_map(jnp.zeros_like, new_aux)))[0]
            return outs, new_aux, grads

        self._jit_bwd = jax.jit(_bwd)

        self.outputs = []
        self._out_raw = None
        self._last_key = _fresh_key()
        # executed jit signatures: one entry per compiled program variant
        # (shape/dtype of every arg + aux, train flag).  The serving layer
        # asserts recompile-free steady state against this set.
        self._jit_cache_keys = set()

    # ------------------------------------------------------------------
    def _sharding(self, name):
        if self._mesh is None:
            return None
        if name in self._arg_groups:
            spec = self._group_specs[self._arg_groups[name]]
            arr = self.arg_dict.get(name)
            if arr is None:
                arr = self.aux_dict.get(name)
            if arr is not None:
                spec = _fit_spec(spec, arr.shape, self._mesh)
            return NamedSharding(self._mesh, spec)
        if name in self._data_names or name.endswith("_label"):
            if "data" in self._mesh.axis_names:
                return NamedSharding(self._mesh, PartitionSpec("data"))
        return NamedSharding(self._mesh, PartitionSpec())

    def _place_arrays(self):
        if self._mesh is None:
            dev = self._devices[0]
            for d in (self.arg_dict, self.aux_dict, self.grad_dict):
                for n, a in d.items():
                    if not _on_device(a._data, dev):
                        a._set_data(jax.device_put(a._data, dev))
            return
        for d in (self.arg_dict, self.aux_dict, self.grad_dict):
            for n, a in d.items():
                a._set_data(jax.device_put(a._data, self._sharding(n)))

    # ------------------------------------------------------------------
    @classmethod
    def simple_bind(cls, symbol, ctx=None, grad_req="write", type_dict=None,
                    shapes=None, data_names=None, group2ctx=None,
                    lint=False):
        shapes = shapes or {}
        if lint:
            # opt-in static pass (mxnet_tpu.analysis) before any trace:
            # error findings abort the bind, warnings go through warnings
            from .analysis import ERROR as _LINT_ERROR
            from .analysis import lint_symbol, render_text
            findings = lint_symbol(symbol, shapes=shapes,
                                   type_dict=type_dict)
            errors = [f for f in findings if f.severity == _LINT_ERROR]
            if errors:
                raise MXNetError("simple_bind lint failed:\n%s"
                                 % render_text(errors))
            if findings:
                import warnings
                warnings.warn("simple_bind lint:\n%s" % render_text(findings))
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
        if arg_shapes is None:
            raise MXNetError(
                "simple_bind: cannot infer all shapes from %r" % (shapes,))
        type_dict = type_dict or {}
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        args = {}
        for n, s in zip(arg_names, arg_shapes):
            dt = np_dtype(type_dict.get(n, "float32"))
            args[n] = NDArray(jnp.zeros(s, dtype=dt))
        aux = {}
        for n, s in zip(aux_names, aux_shapes):
            init = jnp.ones(s, _np.float32) if n.endswith("_var") else \
                jnp.zeros(s, _np.float32)
            aux[n] = NDArray(init)
        if data_names is None:
            data_names = [n for n in shapes if n in arg_names]
        return cls(symbol, ctx, args=args, grad_req=grad_req,
                   aux_states=aux, data_names=data_names,
                   group2ctx=group2ctx)

    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for n, v in kwargs.items():
            if n not in self.arg_dict:
                raise MXNetError("unknown argument %r" % n)
            raw = _raw(v)
            # feeds land on the executor's device/sharding (async transfer
            # overlaps with compute — the PrefetcherIter copy analogue)
            target = self._sharding(n) or self._devices[0]
            if not _on_device(raw, self._devices[0]) or self._mesh is not None:
                raw = jax.device_put(raw, target)
            # dtype-stable feed: a float-bound slot fed uint8 (the narrow
            # uint8 pipeline) or a mismatched float width would change the
            # jit signature and recompile every program — cast on device
            # AFTER the (narrow) transfer instead.  Integer feeds into
            # integer slots pass through untouched.
            bound = self.arg_dict[n]._data.dtype
            if raw.dtype != bound and jnp.issubdtype(bound, jnp.floating) \
                    and (raw.dtype == jnp.uint8
                         or jnp.issubdtype(raw.dtype, jnp.floating)):
                raw = raw.astype(bound)
            self.arg_dict[n]._set_data(raw)
        arg_vals = {n: a._data for n, a in self.arg_dict.items()}
        aux_vals = {n: a._data for n, a in self.aux_dict.items()}
        self._jit_cache_keys.add((
            bool(is_train),
            tuple(sorted((n, tuple(v.shape), str(v.dtype))
                         for n, v in arg_vals.items())),
            tuple(sorted((n, tuple(v.shape), str(v.dtype))
                         for n, v in aux_vals.items()))))
        fn = self._jit_train if is_train else self._jit_infer
        # draw the key eagerly; backward reuses it so dropout masks match
        # between the forward pass and the rematerialized one in the vjp
        self._last_key = _fresh_key()
        outs, new_aux = fn(arg_vals, aux_vals, self._last_key)
        if is_train:
            for n, v in new_aux.items():
                self.aux_dict[n]._set_data(v)
        self._out_raw = outs
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        if self._out_raw is None:
            raise MXNetError("backward called before forward")
        if out_grads is None:
            head = [jnp.ones_like(o) for o in self._out_raw]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            head = [_raw(g) for g in out_grads]
        arg_vals = {n: a._data for n, a in self.arg_dict.items()}
        aux_vals = {n: a._data for n, a in self.aux_dict.items()}
        _outs, _new_aux, grads = self._jit_bwd(arg_vals, aux_vals, head,
                                               self._last_key)
        for n, g in grads.items():
            req = self._grad_req.get(n, "null")
            if req == "null":
                continue
            dst = self.grad_dict.get(n)
            if dst is None:
                self.grad_dict[n] = NDArray(g)
            elif req == "add":
                dst._set_data(dst._data + g)
            else:
                dst._set_data(g)
        self.grad_arrays = [self.grad_dict.get(n) for n in self._arg_names]
        return [self.grad_dict.get(n) for n in self._wrt]

    # ------------------------------------------------------------------
    def jit_cache_keys(self):
        """Signatures executed so far — the jit-cache keys.  jax.jit caches
        one compiled program per signature, so a stable set across a load
        window proves zero steady-state recompiles (serving contract)."""
        return set(self._jit_cache_keys)

    def jit_cache_size(self):
        """Number of compiled program variants.  Prefers the jit's own
        cache counter (counts actual XLA traces) and falls back to the
        tracked signature set."""
        try:
            return int(self._jit_infer._cache_size()
                       + self._jit_train._cache_size()
                       + self._jit_bwd._cache_size())
        except AttributeError:
            return len(self._jit_cache_keys)

    # ------------------------------------------------------------------
    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for n, v in (arg_params or {}).items():
            if n in self.arg_dict:
                self.arg_dict[n]._set_data(
                    _raw(v).astype(self.arg_dict[n]._data.dtype))
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % n)
        for n, v in (aux_params or {}).items():
            if n in self.aux_dict:
                self.aux_dict[n]._set_data(
                    _raw(v).astype(self.aux_dict[n]._data.dtype))
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %r" % n)
        self._place_arrays()

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new data shapes, keeping parameter arrays
        (reference: executor.h:120; jit recompiles per shape — cached)."""
        shapes = {n: kwargs.get(n, self.arg_dict[n].shape)
                  for n in self._data_names} if self._data_names else dict(kwargs)
        new = Executor.simple_bind(
            self._symbol, None,
            grad_req={n: r for n, r in self._grad_req.items()},
            shapes=shapes, data_names=self._data_names)
        for n, a in self.arg_dict.items():
            if n not in self._data_names and n in new.arg_dict and \
                    new.arg_dict[n].shape == a.shape:
                new.arg_dict[n]._set_data(a._data)
        for n, a in self.aux_dict.items():
            if n in new.aux_dict and new.aux_dict[n].shape == a.shape:
                new.aux_dict[n]._set_data(a._data)
        return new

    def __repr__(self):
        return "<Executor %s on %d device(s)>" % (
            self._symbol.name or "group", len(self._devices))


def _fresh_key():
    from . import _rng
    return _rng.next_key()


def _as_nd(x):
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x))


def _raw(x):
    if isinstance(x, NDArray):
        return x._data
    return jnp.asarray(x)


def _on_device(arr, dev):
    try:
        return next(iter(arr.devices())) == dev
    except (AttributeError, TypeError):
        return True
