"""Optimizers (reference: ``python/mxnet/optimizer.py:445-1447`` — SGD with
multi-precision, Signum, FTML, LBSGD, DCASGD, NAG, SGLD, Adam, AdaGrad,
RMSProp, AdaDelta, Ftrl, Adamax, Nadam, Test; plus the ``Updater`` wrapper
with state (de)serialization used by KVStore servers).

Design: every optimizer exposes a *pure functional core*
``_apply(weight, grad, states, lr, wd) -> (new_weight, new_states)`` over raw
jax arrays — so the same update lowers into jitted/pjit training steps (the
TPU analogue of the reference's fused optimizer_op-inl.h kernels) — plus the
reference's imperative ``update(index, weight, grad, state)`` API on top.
"""
from __future__ import annotations

import pickle

import numpy as np
import jax.numpy as jnp

from .base import Registry
from .ndarray import NDArray
from . import ndarray as nd

_REG = Registry("optimizer")


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, momentum=None, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self._extra = kwargs

    @staticmethod
    def register(klass):
        _REG.register(klass)
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        return _REG.create(name, **kwargs)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_low_precision(weight.dtype):
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_low_precision(weight.dtype):
            master, inner = state
            g32 = grad.astype("float32")
            self.update(index, master, g32, inner)
            weight._set_data(master._data.astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("lr_scheduler is set; use scheduler to change lr")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _prep_grad(self, grad):
        g = grad * self.rescale_grad if self.rescale_grad != 1.0 else grad
        if self.clip_gradient is not None:
            c = self.clip_gradient
            g = jnp.clip(g, -c, c)
        return g


def _is_low_precision(dtype):
    """fp16 weights get fp32 master copies under multi_precision in the
    reference (optimizer.py SGD); bf16 is the TPU-native analogue."""
    return dtype == np.float16 or dtype == jnp.bfloat16


register = Optimizer.register
create = Optimizer.create_optimizer


def _raw(x):
    return x._data if isinstance(x, NDArray) else x


def _colocate(arr, like):
    """Replicate a small array onto the mesh a weight lives on, so sparse
    row updates compose with GSPMD placement (single-device arrays can't
    mix with multi-device ones in one op)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    sh = getattr(like, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh.devices.size > 1:
        return jax.device_put(arr, NamedSharding(sh.mesh, PartitionSpec()))
    return arr


@register
class SGD(Optimizer):
    """SGD with momentum + optional fp16 master weights
    (reference: optimizer.py SGD, src/operator/optimizer_op-inl.h sgd_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return None

    def _apply(self, w, g, mom, lr, wd):
        g = self._prep_grad(g) + wd * w
        if mom is None:
            return w - lr * g, None
        new_mom = self.momentum * mom - lr * g
        return w + new_mom, new_mom

    def _op_kwargs(self, lr, wd):
        return dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                    clip_gradient=-1.0 if self.clip_gradient is None
                    else self.clip_gradient)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            _sparse_sgd_update(self, weight, grad, state, lr, wd)
            return
        # dense path goes through the registered fused-update ops, exactly
        # as the reference optimizer does (optimizer.py SGD._update_impl ->
        # sgd_update/sgd_mom_update ops)
        kw = self._op_kwargs(lr, wd)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=weight,
                              momentum=self.momentum,
                              lazy_update=self.lazy_update, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight,
                          lazy_update=self.lazy_update, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray
        if (self.multi_precision and _is_low_precision(weight.dtype)
                and not isinstance(grad, RowSparseNDArray)):
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            master, mom = state
            kw = self._op_kwargs(lr, wd)
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, master, out=weight,
                                     momentum=self.momentum,
                                     lazy_update=self.lazy_update, **kw)
            else:
                nd.mp_sgd_update(weight, grad, master, out=weight,
                                 lazy_update=self.lazy_update, **kw)
        else:
            super().update_multi_precision(index, weight, grad, state)


def _sparse_sgd_update(opt, weight, grad, state, lr, wd):
    """Row-sparse SGD: only touched rows updated (reference:
    optimizer_op-inl.h SGDUpdateRspRspImpl, 'lazy update')."""
    w = _raw(weight)
    idx = _colocate(grad.indices._data.astype(jnp.int32), w)
    gval = _colocate(opt._prep_grad(grad.data._data), w)
    rows = w[idx]
    upd = gval + wd * rows
    if state is not None:
        m = _colocate(_raw(state), w)
        new_m_rows = opt.momentum * m[idx] - lr * upd
        state._set_data(m.at[idx].set(new_m_rows))
        weight._set_data(w.at[idx].add(new_m_rows))
    else:
        weight._set_data(w.at[idx].add(-lr * upd))


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = -1.0 if self.clip_gradient is None else self.clip_gradient
        if state is not None:
            nd.signum_update(weight, grad, state, out=weight, lr=lr, wd=wd,
                             momentum=self.momentum, wd_lh=self.wd_lh,
                             rescale_grad=self.rescale_grad,
                             clip_gradient=clip)
        else:
            nd.signsgd_update(weight, grad, out=weight, lr=lr, wd=wd,
                              rescale_grad=self.rescale_grad,
                              clip_gradient=clip)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        v = nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        d = nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return (d, v, z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        nd.ftml_update(weight, grad, d, v, z, out=weight, lr=lr, wd=wd, t=t,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon,
                       rescale_grad=self.rescale_grad,
                       clip_grad=-1.0 if self.clip_gradient is None
                       else self.clip_gradient)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise scaling (reference LBSGD)."""

    def __init__(self, momentum=0.9, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)

    def update(self, index, weight, grad, state):
        w, g = _raw(weight), _raw(grad)
        wnorm = jnp.linalg.norm(w)
        gnorm = jnp.linalg.norm(g * self.rescale_grad)
        lars = jnp.where(gnorm > 0, wnorm / (gnorm + self.wd * wnorm + 1e-9), 1.0)
        lars = jnp.clip(lars, 0.0, 10.0)
        saved_lr = self.lr
        try:
            self.lr = float(saved_lr)  # lars folded via grad scale below
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            new_w, new_m = self._apply(
                w, g * lars, _raw(state) if state is not None else None, lr, wd)
            weight._set_data(new_w)
            if state is not None:
                state._set_data(new_m)
        finally:
            self.lr = saved_lr


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w = _raw(weight)
        g = self._prep_grad(_raw(grad)) + wd * w
        mom, prev = state
        comp = g + self.lamda * g * g * (w - _raw(prev))
        if mom is not None:
            m = self.momentum * _raw(mom) - lr * comp
            mom._set_data(m)
            new_w = w + m
        else:
            new_w = w - lr * comp
        prev._set_data(w)
        weight._set_data(new_w)


@register
class NAG(SGD):
    """Nesterov accelerated SGD."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w = _raw(weight)
        g = self._prep_grad(_raw(grad)) + wd * w
        if state is not None:
            m = self.momentum * _raw(state) + g
            state._set_data(m)
            new_w = w - lr * (g + self.momentum * m)
        else:
            new_w = w - lr * g
        weight._set_data(new_w)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w = _raw(weight)
        g = self._prep_grad(_raw(grad)) + wd * w
        from . import _rng
        import jax
        noise = jax.random.normal(_rng.next_key(), w.shape, w.dtype) * jnp.sqrt(lr)
        weight._set_data(w - lr / 2 * g + noise)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def _apply(self, w, g, m, v, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        b1, b2 = self.beta1, self.beta2
        coef1 = 1.0 - b1 ** t
        coef2 = 1.0 - b2 ** t
        lr_t = lr * (coef2 ** 0.5) / coef1
        new_m = b1 * m + (1 - b1) * g
        new_v = b2 * v + (1 - b2) * g * g
        new_w = w - lr_t * new_m / (jnp.sqrt(new_v) + self.epsilon)
        return new_w, new_m, new_v

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        m, v = state
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            w = _raw(weight)
            idx = _colocate(grad.indices._data.astype(jnp.int32), w)
            gval = _colocate(self._prep_grad(grad.data._data), w) + wd * w[idx]
            b1, b2 = self.beta1, self.beta2
            lr_t = lr * ((1 - b2 ** t) ** 0.5) / (1 - b1 ** t)
            m_raw = _colocate(_raw(m), w)
            v_raw = _colocate(_raw(v), w)
            m_rows = b1 * m_raw[idx] + (1 - b1) * gval
            v_rows = b2 * v_raw[idx] + (1 - b2) * gval * gval
            m._set_data(m_raw.at[idx].set(m_rows))
            v._set_data(v_raw.at[idx].set(v_rows))
            weight._set_data(w.at[idx].add(-lr_t * m_rows / (jnp.sqrt(v_rows) + self.epsilon)))
            return
        # dense path: bias-corrected lr into the fused adam_update op, as
        # the reference optimizer does (optimizer.py Adam.update)
        lr_t = lr * ((1 - self.beta2 ** t) ** 0.5) / (1 - self.beta1 ** t)
        nd.adam_update(weight, grad, m, v, out=weight, lr=lr_t, wd=wd,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=-1.0 if self.clip_gradient is None
                       else self.clip_gradient,
                       lazy_update=self.lazy_update)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            # row-wise history/weight update: only touched rows read/written
            # (reference: _sparse_adagrad_update, optimizer_op.cc:651)
            w = _raw(weight)
            idx = _colocate(grad.indices._data.astype(jnp.int32), w)
            g = _colocate(self._prep_grad(grad.data._data), w)
            if wd:
                g = g + wd * w[idx]
            h = _colocate(_raw(state), w)
            h_rows = h[idx] + g * g
            state._set_data(h.at[idx].set(h_rows))
            weight._set_data(w.at[idx].add(
                -lr * g / (jnp.sqrt(h_rows) + self.float_stable_eps)))
            return
        nd.sparse_adagrad_update(weight, grad, state, out=weight, lr=lr,
                                 wd=wd, epsilon=self.float_stable_eps,
                                 rescale_grad=self.rescale_grad,
                                 clip_gradient=-1.0 if self.clip_gradient
                                 is None else self.clip_gradient)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        zeros = lambda: nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        if self.centered:
            return (zeros(), zeros(), zeros())  # n, g, delta
        return (zeros(),)  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                  rescale_grad=self.rescale_grad,
                  clip_gradient=-1.0 if self.clip_gradient is None
                  else self.clip_gradient,
                  clip_weights=-1.0 if not self.clip_weights
                  else self.clip_weights)
        if self.centered:
            n, mean_g, delta = state
            nd.rmspropalex_update(weight, grad, n, mean_g, delta, out=weight,
                                  gamma2=self.gamma2, **kw)
        else:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=weight, **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        w = _raw(weight)
        g = self._prep_grad(_raw(grad)) + wd * w
        acc_g, acc_delta = state
        ag = self.rho * _raw(acc_g) + (1 - self.rho) * g * g
        delta = jnp.sqrt(_raw(acc_delta) + self.epsilon) / jnp.sqrt(
            ag + self.epsilon) * g
        ad = self.rho * _raw(acc_delta) + (1 - self.rho) * delta * delta
        acc_g._set_data(ag); acc_delta._set_data(ad)
        weight._set_data(w - delta)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),  # z
                nd.zeros(weight.shape, ctx=weight.context))  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=weight, lr=lr, wd=wd,
                       lamda1=self.lamda1, beta=self.beta,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=-1.0 if self.clip_gradient is None
                       else self.clip_gradient)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        w = _raw(weight)
        g = self._prep_grad(_raw(grad)) + wd * w
        lr_t = lr / (1 - self.beta1 ** t)
        m, u = state
        m_t = self.beta1 * _raw(m) + (1 - self.beta1) * g
        u_t = jnp.maximum(self.beta2 * _raw(u), jnp.abs(g))
        m._set_data(m_t); u._set_data(u_t)
        weight._set_data(w - lr_t * m_t / (u_t + 1e-8))


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        w = _raw(weight)
        g = self._prep_grad(_raw(grad)) + wd * w
        mom_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mom_tp1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * mom_t
        sched_next = self.m_schedule * mom_tp1
        m, v = state
        g_prime = g / (1.0 - self.m_schedule)
        m_t = self.beta1 * _raw(m) + (1 - self.beta1) * g
        v_t = self.beta2 * _raw(v) + (1 - self.beta2) * g * g
        m_prime = m_t / (1.0 - sched_next)
        v_prime = v_t / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - mom_t) * g_prime + mom_tp1 * m_prime
        m._set_data(m_t); v._set_data(v_t)
        weight._set_data(w - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon))


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        w = _raw(weight)
        weight._set_data(w + _raw(grad) * self.rescale_grad)
        state._set_data(_raw(weight))


# aliases like the reference
ccSGD = SGD


class Updater:
    """Wraps an optimizer for KVStore use; serializable states
    (reference: optimizer.py:1460 get_updater, :1498-1507)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(to_np(x) for x in s)
            return s.asnumpy() if isinstance(s, NDArray) else s
        states = {k: to_np(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, self.optimizer))
        return pickle.dumps(states)

    def set_states(self, states):
        loaded = pickle.loads(states)
        if isinstance(loaded, tuple) and len(loaded) == 2 and isinstance(
                loaded[1], Optimizer):
            states, self.optimizer = loaded
        else:
            states = loaded

        def to_nd(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(to_nd(x) for x in s)
            return nd.array(s, dtype=s.dtype) if isinstance(s, np.ndarray) else s
        self.states = {k: to_nd(v) for k, v in states.items()}
        self.states_synced = {k: False for k in self.states}


def get_updater(optimizer):
    return Updater(optimizer)
