"""Profiler: the `mx.profiler` namespace.

Reference: ``python/mxnet/profiler.py`` (426 LoC: set_config/set_state,
dump, scoped Task/Frame/Marker/Domain) over the native profiler
(``src/profiler/profiler.h:256``) which records per-op events into
chrome://tracing JSON (``DumpProfile:304``).

TPU-native design: two complementary recorders —

- **Device timeline**: ``jax.profiler`` traces (TensorBoard / perfetto)
  capture the XLA/TPU side; ``set_state('run')`` starts a trace into the
  configured directory, ``dump()``/``set_state('stop')`` ends it.
- **Host op log**: when profiling is on, the imperative ``invoke`` path and
  user Task/Frame/Marker scopes append events to an in-process buffer that
  ``dumps()`` renders as chrome://tracing JSON — same file format the
  reference emits, so existing trace-viewing workflows carry over.

Zero overhead when off (a single bool check, like the reference's
profiler hook in ThreadedEngine::ExecuteOprBlock).
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Domain", "Task", "Frame", "Marker", "Counter",
           "PipelineStats",
           "profiler_set_config", "profiler_set_state"]

_lock = threading.Lock()
_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": False, "profile_api": False,
           "aggregate_stats": False, "tensorboard_dir": None}
_state = "stop"
_paused = False
# module-level flag read by the hot invoke() path: one attribute load when off
_PROFILING = False
_events = []
# the event buffer is BOUNDED: an unbounded list on a long profiled run
# grows without limit and every append past RAM pressure stalls the hot
# path.  Past the cap, events are counted as dropped instead of stored —
# the count is surfaced in dumps() metadata so a truncated trace is
# never mistaken for a complete one.
_MAX_EVENTS = int(os.environ.get("MXTPU_PROFILER_MAX_EVENTS", "500000"))
_dropped = 0
# free-form per-process metadata included in dumps() output: rank, clock
# origin, PS clock offsets — what tools/trace_merge.py aligns fleets by
_metadata = {}
_start_time = None
_jax_trace_active = False


def is_running():
    return _PROFILING


def set_config(**kwargs):
    """Configure (reference: profiler.py set_config).  Recognized kwargs:
    filename, profile_all, profile_symbolic, profile_imperative,
    profile_memory, profile_api, aggregate_stats, tensorboard_dir."""
    if _state == "run":
        raise RuntimeError("cannot set_config while profiler is running")
    for k, v in kwargs.items():
        _config[k] = v


profiler_set_config = set_config


def set_state(state_name="stop", profile_process="worker"):
    """Start/stop profiling (reference: profiler.py set_state)."""
    global _state, _start_time, _jax_trace_active
    if state_name not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state_name == "run" and _state != "run":
        global _dropped
        with _lock:
            _events.clear()
            _dropped = 0
        _start_time = time.perf_counter_ns()
        tb = _config.get("tensorboard_dir")
        if tb:
            import jax
            os.makedirs(tb, exist_ok=True)
            jax.profiler.start_trace(tb)
            _jax_trace_active = True
    if state_name == "stop" and _state == "run":
        if _jax_trace_active:
            import jax
            jax.profiler.stop_trace()
            _jax_trace_active = False
    _state = state_name
    _sync_flag()


profiler_set_state = set_state


def state():
    return _state


def _sync_flag():
    global _PROFILING
    _PROFILING = _state == "run" and not _paused


def pause(profile_process="worker"):
    global _paused
    _paused = True
    _sync_flag()


def resume(profile_process="worker"):
    global _paused
    _paused = False
    _sync_flag()


def _now_us():
    return (time.perf_counter_ns() - (_start_time or 0)) / 1000.0


def _append_locked(event):
    """Append under ``_lock`` honoring the buffer cap (callers hold no
    lock; the cap check and append are one atomic section)."""
    global _dropped
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append(event)


def record_event(name, category, t_start_us, dur_us, args=None):
    if not is_running():
        return
    _append_locked({"name": name, "cat": category, "ph": "X",
                    "ts": t_start_us, "dur": dur_us, "pid": os.getpid(),
                    "tid": threading.get_ident() % 100000,
                    "args": args or {}})


def record_instant(name, category, args=None):
    if not is_running():
        return
    _append_locked({"name": name, "cat": category, "ph": "i",
                    "ts": _now_us(), "pid": os.getpid(),
                    "tid": threading.get_ident() % 100000, "s": "p",
                    "args": args or {}})


def set_metadata(**kv):
    """Attach per-process metadata to the trace (rank, clock offsets...);
    surfaced under ``metadata`` in :func:`dumps` output, where
    ``tools/trace_merge.py`` reads it to align per-rank timelines."""
    with _lock:
        _metadata.update(kv)


def dropped_events():
    """Events dropped past the ``MXTPU_PROFILER_MAX_EVENTS`` cap."""
    with _lock:
        return _dropped


def dumps(reset=False):
    """Return the chrome://tracing JSON string (reference: dumps).

    Serialization runs OUTSIDE the lock — only the list copy is locked —
    so concurrent emitters never stall behind ``json.dumps`` of a large
    trace.  The top-level ``metadata`` object carries the process's
    clock origin (``perf_origin_ns``), pid, ``dropped_events`` (buffer
    cap overflow — nonzero means the trace is truncated) and anything
    installed via :func:`set_metadata`."""
    global _dropped
    with _lock:
        events = list(_events)
        meta = dict(_metadata)
        meta.update({"pid": os.getpid(), "perf_origin_ns": _start_time,
                     "dropped_events": _dropped,
                     "event_cap": _MAX_EVENTS})
        if reset:
            _events.clear()
            _dropped = 0
    return json.dumps({"traceEvents": events, "displayTimeUnit": "ms",
                       "metadata": meta}, indent=1)


def dump(finished=True, profile_process="worker"):
    """Write the trace JSON to the configured filename (reference: dump)."""
    with open(_config["filename"], "w") as f:
        f.write(dumps())
    if finished and _jax_trace_active:
        set_state("stop")


class _Scope:
    """Base for scoped profiler objects; also usable via start()/stop()."""
    _category = "scope"

    def __init__(self, name, domain=None):
        self.name = name if domain is None else "%s::%s" % (domain.name, name)
        self._t0 = None
        self._annotation = None

    def start(self):
        self._t0 = _now_us()
        if is_running():
            import jax
            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        return self

    def stop(self):
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None
        if self._t0 is not None:
            record_event(self.name, self._category, self._t0,
                         _now_us() - self._t0)
            self._t0 = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class Domain:
    """Grouping namespace for tasks/counters (reference: profiler.Domain)."""

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return "Domain(%s)" % self.name

    def new_task(self, name):
        return Task(name, self)

    def new_frame(self, name):
        return Frame(name, self)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class Task(_Scope):
    _category = "task"


class Frame(_Scope):
    _category = "frame"


class Counter:
    """Numeric counter series (reference: profiler.Counter).

    Thread-safe: ``increment``/``decrement`` are atomic read-modify-write
    under a per-counter lock — concurrent emitters (serving handler
    threads, pipeline workers) never lose updates."""

    def __init__(self, domain, name, value=None):
        self.name = "%s::%s" % (domain.name, name)
        self._value = 0
        self._vlock = threading.Lock()
        if value is not None:
            self.set_value(value)

    def _emit(self, value):
        if is_running():
            _append_locked({"name": self.name, "ph": "C", "ts": _now_us(),
                            "pid": os.getpid(),
                            "args": {"value": value}})

    def set_value(self, value):
        with self._vlock:
            self._value = value
        self._emit(value)

    def increment(self, delta=1):
        with self._vlock:
            self._value += delta
            value = self._value
        self._emit(value)

    def decrement(self, delta=1):
        self.increment(-delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant marker (reference: profiler.Marker)."""

    def __init__(self, domain, name):
        self.name = "%s::%s" % (domain.name, name)

    def mark(self, scope="process"):
        record_instant(self.name, "marker")


class PipelineStats:
    """Per-stage counters for a data pipeline (io/pipeline.py): reorder-
    queue depth, per-worker busy time, consumer stall time, respawns.

    The reference surfaces the same signals ad hoc (the prefetcher's
    ``dmlc::ThreadedIter`` queue and per-thread decode timers); here they
    are one thread-safe accumulator whose ``snapshot()`` feeds both
    ``ImagePipelineIter.stats`` consumers and the bench's stall accounting.
    When the profiler is running, queue depth is also emitted as a Counter
    series so the chrome trace shows the feed pipeline next to the ops.

    The same accumulator carries the run-ahead dispatch counters
    (``on_dispatch``/``on_backpressure`` — engine.py's in-flight ring
    depth and backpressure stall time), so a trainer's ``dispatch_stats``
    and an iterator's feed stats render through one snapshot shape.
    """

    def __init__(self, num_workers=0, name="io.pipeline"):
        self._lock = threading.Lock()
        self._name = name
        self._t0 = time.perf_counter()
        self._busy_s = {}            # worker id -> cumulative decode time
        self._stall_s = 0.0          # consumer time blocked on the ring
        self._batches = 0
        self._depth_max = 0
        self._respawns = 0
        self._respawns_epoch = 0     # reset by on_epoch (the storm budget)
        self._num_workers = num_workers
        self._qd_tick = 0            # 1/8 sampling for queue-growth feed
        domain = Domain(name)
        self._counter = domain.new_counter("queue_depth")
        # run-ahead dispatch accounting (engine.py / DataParallelTrainer):
        # how deep the in-flight ring got, and how long the dispatcher was
        # blocked waiting on its oldest step (backpressure)
        self._dispatched = 0
        self._inflight_max = 0
        self._dispatch_stall_s = 0.0
        self._inflight_counter = domain.new_counter("inflight_steps")
        # one pane of glass: this accumulator is also a telemetry metrics
        # source — snapshot() keys become mxtpu_pipeline_* gauges labeled
        # by pipeline name (weakly held: a dead iterator drops out)
        from . import telemetry as _tele
        _tele.registry().register_collector(self._metrics_samples,
                                            name="pipeline:" + name)

    def _metrics_samples(self):
        from . import telemetry as _tele
        return _tele.flatten_samples("mxtpu_pipeline", self.snapshot(),
                                     labels={"name": self._name})

    def on_batch(self, worker, busy_s, queue_depth):
        with self._lock:
            self._busy_s[worker] = self._busy_s.get(worker, 0.0) + busy_s
            self._batches += 1
            self._depth_max = max(self._depth_max, queue_depth)
        self._counter.set_value(queue_depth)
        # queue-growth anomaly baseline (perf.queue_growth): a reorder
        # queue rising above its EWMA baseline is the dying-slow
        # signature the doctor flags before the run dies.  Sampled 1/8
        # (growth is a trend, not a per-batch event) to keep the armed
        # per-step cost inside the <=1% bench budget.
        self._qd_tick += 1
        if not (self._qd_tick & 7):
            from . import telemetry as _tele
            if _tele._ENABLED:
                _tele.attribution().note_queue_depth(self._name,
                                                     queue_depth)

    def on_wait(self, stall_s):
        with self._lock:
            self._stall_s += stall_s

    def on_respawn(self):
        with self._lock:
            self._respawns += 1
            self._respawns_epoch += 1

    def on_epoch(self):
        """Epoch boundary: reset the per-epoch respawn counter (the unit
        of ``ImagePipelineIter``'s ``max_respawns`` storm budget)."""
        with self._lock:
            self._respawns_epoch = 0

    def on_dispatch(self, inflight):
        """A step was dispatched with ``inflight`` steps now un-synchronized
        (the engine's run-ahead ring depth at dispatch time)."""
        with self._lock:
            self._dispatched += 1
            self._inflight_max = max(self._inflight_max, inflight)
        self._inflight_counter.set_value(inflight)
        self._qd_tick += 1
        if not (self._qd_tick & 7):
            from . import telemetry as _tele
            if _tele._ENABLED:
                _tele.attribution().note_queue_depth(
                    self._name + ".inflight", inflight)

    def on_backpressure(self, stall_s):
        """The dispatcher blocked ``stall_s`` waiting on its oldest
        in-flight step (ring full: the device is the bottleneck)."""
        with self._lock:
            self._dispatch_stall_s += stall_s

    def snapshot(self):
        """Aggregate view: ``worker_utilization`` is decode time over
        (workers × wall) — how busy the pool is; ``stall_pct`` is the
        fraction of wall time the consumer spent blocked waiting for a
        batch — >0 means the pipeline (not the consumer) is the
        bottleneck."""
        with self._lock:
            wall = max(1e-9, time.perf_counter() - self._t0)
            busy = sum(self._busy_s.values())
            util = busy / (wall * self._num_workers) \
                if self._num_workers else 0.0
            return {
                "batches": self._batches,
                "wall_s": round(wall, 3),
                "worker_busy_s": round(busy, 3),
                "worker_utilization": round(util, 4),
                "stall_s": round(self._stall_s, 3),
                "stall_pct": round(100.0 * self._stall_s / wall, 2),
                "queue_depth_max": self._depth_max,
                "respawns": self._respawns,
                "respawns_epoch": self._respawns_epoch,
                "dispatched_steps": self._dispatched,
                "inflight_max": self._inflight_max,
                "dispatch_stall_s": round(self._dispatch_stall_s, 3),
            }
