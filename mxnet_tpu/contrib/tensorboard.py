"""TensorBoard logging callback (reference: python/mxnet/contrib/
tensorboard.py — LogMetricsCallback).  Gated on an available SummaryWriter
implementation (tensorboardX / torch.utils.tensorboard); raises a clear
error otherwise."""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


def _writer(logging_dir):
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from tensorboardX import SummaryWriter
        return SummaryWriter(logging_dir)
    except ImportError as e:
        raise ImportError(
            "LogMetricsCallback requires torch.utils.tensorboard or "
            "tensorboardX") from e


class LogMetricsCallback:
    """Batch-end callback streaming metrics to TensorBoard."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _writer(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
