"""Model quantization driver (reference: python/mxnet/contrib/
quantization.py — quantize_model calibration flow over the int8 ops).

TPU-native: calibration collects per-layer min/max over a DataIter; the
returned (symbol, params) pair carries quantize_v2 nodes with calibrated
ranges, so inference runs int8 matmuls on the MXU.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from .. import symbol as sym

__all__ = ["quantize_model", "calib_graph", "optimal_threshold"]


# -- entropy (KL) calibration --------------------------------------------
# Reference: python/mxnet/contrib/quantization.py:253 _get_optimal_threshold
# — the TensorRT-style histogram/KL-divergence threshold search.  Naive
# min/max calibration lets one outlier blow up the scale; the entropy mode
# picks the clip threshold whose 255-level quantized distribution is
# closest (in KL divergence) to the clipped fp32 distribution.

_NUM_HIST_BINS = 8001
_NUM_QUANT_BINS = 255


def _smoothed_kl(p, q):
    """KL(p || q) with the zero-bin smoothing the calibration literature
    uses: mass from q's empty bins that are non-empty in p is redistributed
    so the divergence stays finite."""
    p = p.astype(np.float64)
    q = q.astype(np.float64)
    eps = 1e-4
    p_nz = p > 0
    q_z = (q == 0) & p_nz
    # move eps into q's problem bins, taking it from its non-empty ones
    if q_z.any():
        take = eps * q_z.sum() / max(1, (q > 0).sum())
        q = np.where(q_z, eps, np.where(q > 0, q - take, 0.0))
    ps = p[p_nz] / p.sum()
    qs = q[p_nz] / q.sum()
    return float(np.sum(ps * np.log(ps / np.maximum(qs, 1e-12))))


def optimal_threshold(hist, hist_edges,
                      num_quantized_bins=_NUM_QUANT_BINS):
    """Pick the |threshold| minimizing KL(clipped fp32 dist || int8 dist).

    ``hist`` is a symmetric histogram over ``[-amax, amax]``.  For every
    candidate half-width ``i`` the central ``2i+1`` bins are kept (outlier
    mass folded into the edge bins), down-quantized to
    ``num_quantized_bins`` levels, expanded back, and scored by KL
    divergence (reference: _get_optimal_threshold:253)."""
    hist = np.asarray(hist, np.float64).copy()
    num_bins = hist.size
    zero = num_bins // 2
    # exclude the zero bin: zero is exactly representable at any threshold,
    # and after relu its spike would dominate the distributions, washing
    # out the clipping cost of every candidate (TensorRT's calibration
    # skips bin 0 for the same reason)
    hist[zero] = 0.0
    # start at num_quantized_bins//2 like the reference
    # (_get_optimal_threshold:253) so the tightest candidate is considered
    half_start = num_quantized_bins // 2
    best = (np.inf, float(hist_edges[-1]))
    for i in range(half_start, zero + 1):
        lo, hi = zero - i, zero + i + 1
        sliced = hist[lo:hi]
        # p: the clipped reference distribution — outlier mass folded into
        # the boundary bins
        p = sliced.copy()
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        if p.sum() == 0:
            continue
        # q: the int8 rendition, built from the *unfolded* slice — the
        # folded outlier mass present in p but absent from q is exactly
        # the clipping cost KL charges this candidate with
        n = p.size
        idx = (np.arange(n) * num_quantized_bins // n)
        q_groups = np.bincount(idx, weights=sliced,
                               minlength=num_quantized_bins)
        # each group's mass spread uniformly over its non-empty source bins
        nonzero = np.bincount(idx, weights=(p > 0).astype(np.float64),
                              minlength=num_quantized_bins)
        with np.errstate(divide="ignore", invalid="ignore"):
            per_bin = np.where(nonzero > 0, q_groups / nonzero, 0.0)
        q = np.where(p > 0, per_bin[idx], 0.0)
        kl = _smoothed_kl(p, q)
        if kl < best[0]:
            th = float(max(abs(hist_edges[lo]), abs(hist_edges[hi])))
            best = (kl, th)
    return best[1]


def _collect_layer_histograms(symbol, arg_params, aux_params, calib_data,
                              num_calib_examples, data_names, stats):
    """Second calibration pass: per-layer histograms over the naive
    [-amax, amax] range (reference: _LayerHistogramCollector)."""
    from ..module.module import Module
    internals = symbol.get_internals()
    outputs = list(stats.keys())
    group = sym.Group([internals[o] for o in outputs])
    mod = Module(group, data_names=data_names, label_names=None)
    mod.bind(calib_data.provide_data, for_training=False)
    mod.set_params(arg_params, aux_params, allow_missing=True,
                   allow_extra=True)
    hists = {}
    edges = {}
    for name in outputs:
        lo, hi = stats[name]
        amax = max(abs(lo), abs(hi)) or 1.0
        hists[name] = np.zeros(_NUM_HIST_BINS, np.float64)
        edges[name] = np.linspace(-amax, amax, _NUM_HIST_BINS + 1)
    seen = 0
    calib_data.reset()
    for batch in calib_data:
        mod.forward(batch, is_train=False)
        for name, out in zip(outputs, mod.get_outputs()):
            a = out.asnumpy().ravel()
            h, _ = np.histogram(a, bins=edges[name])
            hists[name] += h
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return hists, edges


def _collect_layer_stats(symbol, arg_params, aux_params, calib_data,
                         num_calib_examples, data_names, label_names):
    """Run calibration batches through the fp32 graph collecting per-output
    min/max (reference: _collect_layer_output_min_max)."""
    from ..module.module import Module
    internals = symbol.get_internals()
    outputs = [o for o in internals.list_outputs()
               if o.endswith("_output") or o in data_names]
    group = sym.Group([internals[o] for o in outputs])
    mod = Module(group, data_names=data_names, label_names=None)
    mod.bind(calib_data.provide_data, for_training=False)
    mod.set_params(arg_params, aux_params, allow_missing=True,
                   allow_extra=True)
    stats = {o: (np.inf, -np.inf) for o in outputs}
    seen = 0
    calib_data.reset()
    for batch in calib_data:
        mod.forward(batch, is_train=False)
        for name, out in zip(outputs, mod.get_outputs()):
            a = out.asnumpy()
            lo, hi = stats[name]
            stats[name] = (min(lo, float(a.min())), max(hi, float(a.max())))
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return stats


def _entry_range_key(entry):
    node, _ = entry
    return node.name if node.op is None else node.name + "_output"


def _graph_rewrite(symbol, hook):
    """Memoized clone of a symbol graph with a per-node rewrite hook — the
    single walker behind every quantization pass (each used to hand-roll
    its own memo/clone recursion).

    ``hook(node, new, clone)`` runs after ``new`` (a fresh ``_Node`` with
    cloned inputs) is built; ``clone`` maps original nodes to their copies
    (memoized).  A non-None return replaces ``new`` in the memo so every
    downstream consumer rewires to it."""
    from ..symbol.symbol import Symbol, _Node

    memo = {}

    def clone(node):
        if id(node) in memo:
            return memo[id(node)]
        new = _Node(node.op, node.name, dict(node.attrs), [], node._is_aux)
        memo[id(node)] = new  # register before recursing into inputs
        new.inputs = [(clone(c), i) for c, i in node.inputs]
        repl = hook(node, new, clone)
        if repl is not None and repl is not new:
            memo[id(node)] = repl
            return repl
        return new

    return Symbol([(clone(n), i) for n, i in symbol._outputs])


def _consumer_sets(symbol, with_indices=False):
    """{id(node): set of distinct consumers} with ``"head"`` marking graph
    outputs.  A multi-output producer feeding one consumer through several
    edges still counts as a single consumer.  With ``with_indices`` also
    returns {id(node): set of output indices read by any consumer} so
    rewrites can tell a data-output edge from a stats-output edge."""
    consumers = {}
    out_idx = {}
    seen = set()

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child, i in node.inputs:
            consumers.setdefault(id(child), set()).add(id(node))
            out_idx.setdefault(id(child), set()).add(i)
            walk(child)

    for n, i in symbol._outputs:
        consumers.setdefault(id(n), set()).add("head")
        out_idx.setdefault(id(n), set()).add(i)
        walk(n)
    return (consumers, out_idx) if with_indices else consumers


def fold_batch_norms(symbol, arg_params, aux_params):
    """Fold Convolution→BatchNorm chains into the conv weights/bias — the
    standard inference-graph transform (the reference's MKLDNN subgraph
    fuse pass does the same ahead of int8 rewriting).  Inference only:
    uses the moving statistics.

    Returns (new_symbol, new_arg_params, new_aux_params)."""
    from ..symbol.symbol import _Node

    arg_params = dict(arg_params)
    aux_params = dict(aux_params)
    consumers, out_idx = _consumer_sets(symbol, with_indices=True)

    def hook(node, new, clone):
        if node.op != "BatchNorm" or not node.inputs:
            return None
        src, _src_out = node.inputs[0]
        if src.op != "Convolution" or \
                len(consumers.get(id(src), ())) != 1:
            return None
        # a consumer wired to BN output 1/2 (mean/var) would be silently
        # rewired to a nonexistent conv output — only fold data-only BNs
        if out_idx.get(id(node), {0}) != {0}:
            return None
        # the BN must normalize the conv's channel axis: channels-last
        # convs carry channels on the minor axis, channels-first on axis 1
        bn_axis = int(_reg_canon(node.attrs.get("axis", 1)))
        kernel = src.attrs.get("kernel")
        nsp = len(_attr_tuple(kernel)) if kernel else 2
        ch_axis = nsp + 1 if src.attrs.get("layout") in (
            "NWC", "NHWC", "NDHWC") else 1
        if bn_axis != ch_axis:
            return None
        wname = src.name + "_weight"
        gname, bname = node.name + "_gamma", node.name + "_beta"
        mname, vname = node.name + "_moving_mean", node.name + "_moving_var"
        if wname not in arg_params or mname not in aux_params:
            return None
        eps = float(_reg_canon(node.attrs.get("eps", 1e-3)))
        fix_gamma = _reg_canon(node.attrs.get("fix_gamma", True))
        mean = aux_params[mname].asnumpy()
        var = aux_params[vname].asnumpy()
        gamma = np.ones_like(mean) if fix_gamma else \
            arg_params[gname].asnumpy()
        beta = arg_params[bname].asnumpy() if bname in arg_params \
            else np.zeros_like(mean)
        scale = gamma / np.sqrt(var + eps)
        shift = beta - mean * scale
        w = arg_params[wname].asnumpy()
        # output channels are axis 0 in both OIHW and O*kernel*I layouts
        arg_params[wname] = nd.array(
            w * scale.reshape((-1,) + (1,) * (w.ndim - 1)))
        cbias = src.name + "_bias"
        had_bias = not _reg_canon(src.attrs.get("no_bias", False))
        if had_bias and cbias in arg_params:
            shift = arg_params[cbias].asnumpy() * scale + shift
        arg_params[cbias] = nd.array(shift)
        folded = clone(src)
        conv = _Node(src.op, src.name, dict(src.attrs), list(folded.inputs))
        conv.attrs["no_bias"] = False
        if not had_bias:
            bvar = _Node(None, cbias, {"__shape__": str(shift.shape),
                                       "__dtype__": "float32"})
            conv.inputs = conv.inputs[:2] + [(bvar, 0)]
        return conv

    out = _graph_rewrite(symbol, hook)
    # drop the folded BN params so set_params doesn't complain
    live = {n.name for n in out._nodes() if n.op is None}
    arg_params = {k: v for k, v in arg_params.items()
                  if k in live or not k.endswith(("_gamma", "_beta"))}
    aux_params = {k: v for k, v in aux_params.items() if k in live}
    return out, arg_params, aux_params


def _reg_canon(v):
    from ..ops.registry import canonicalize
    return canonicalize(v)


# attrs each quantized op inherits from its fp32 node
_QCONV_ATTRS = ("kernel", "stride", "dilate", "pad", "num_filter",
                "num_group", "layout")
_QPOOL_ATTRS = ("kernel", "pool_type", "global_pool", "pooling_convention",
                "stride", "pad", "count_include_pad", "layout")
_QUANTIZABLE = {"FullyConnected", "Convolution", "Pooling"}


def _rewrite_int8(symbol, arg_params, th_dict, excluded):
    """Replace calibrated FullyConnected/Convolution/Pooling nodes with
    quantize_v2 → quantized op → dequantize (+ fp32 bias) subgraphs — the
    quantize_graph_pass.cc analogue (reference also covers conv and
    pooling: quantized_conv.cu, quantized_pooling.cc).  Layers without a
    calibrated input range, or in `excluded`, stay fp32."""
    from ..symbol.symbol import _Node

    def hook(node, new, clone):
        if node.op not in _QUANTIZABLE or node.name in excluded:
            return None
        rng = th_dict.get(_entry_range_key(node.inputs[0]))
        if rng is None:
            return None
        lo, hi = rng
        data_entry = new.inputs[0]
        qdata = _Node("_contrib_quantize_v2", node.name + "_qdata",
                      {"out_type": "int8", "min_calib_range": lo,
                       "max_calib_range": hi}, [data_entry])

        if node.op == "Pooling":
            qpool = _Node("_contrib_quantized_pooling", node.name + "_int8",
                          {k: node.attrs[k] for k in _QPOOL_ATTRS
                           if k in node.attrs},
                          [(qdata, 0), (qdata, 1), (qdata, 2)])
            return _Node("_contrib_dequantize", node.name + "_deq", {},
                         [(qpool, 0), (qpool, 1), (qpool, 2)])

        wname = node.name + "_weight"
        if wname + "_quantized" not in arg_params:
            return None

        def qvar(suffix):
            full = wname + suffix
            arr = arg_params[full]
            return _Node(None, full,
                         {"__shape__": str(tuple(arr.shape)),
                          "__dtype__": str(np.dtype(arr.dtype).name)})

        wq = qvar("_quantized")
        wmn = qvar("_min")
        wmx = qvar("_max")
        has_bias = len(node.inputs) > 2
        if node.op == "FullyConnected":
            attrs = {"num_hidden": node.attrs.get("num_hidden"),
                     "no_bias": True,
                     "flatten": node.attrs.get("flatten", True)}
            qop_name = "_contrib_quantized_fully_connected"
        else:
            attrs = {k: node.attrs[k] for k in _QCONV_ATTRS
                     if k in node.attrs}
            attrs["no_bias"] = True
            qop_name = "_contrib_quantized_conv"
        qop = _Node(qop_name, node.name + "_int8", attrs,
                    [(qdata, 0), (wq, 0), (qdata, 1), (qdata, 2),
                     (wmn, 0), (wmx, 0)])
        deq = _Node("_contrib_dequantize", node.name + "_deq",
                    {}, [(qop, 0), (qop, 1), (qop, 2)])
        if not has_bias:
            return deq
        bias_entry = new.inputs[2]
        bname = node.name + "_bias"
        if bias_entry[0].op is None and bname in arg_params:
            # no fp32 node derives its shape anymore — pin it on the var
            bias_entry[0].attrs.setdefault(
                "__shape__", str(tuple(arg_params[bname].shape)))
        if node.op == "Convolution" and \
                node.attrs.get("layout") not in ("NWC", "NHWC", "NDHWC"):
            # bias broadcasts over channels: (C,) -> (1, C, 1, ...);
            # channels-last layouts broadcast on the minor axis natively
            nsp = len(_attr_tuple(node.attrs.get("kernel", (1, 1))))
            bshape = (1, -1) + (1,) * nsp
            bias_entry = (_Node("Reshape", node.name + "_bias_rs",
                                {"shape": str(bshape)}, [bias_entry]), 0)
        return _Node("broadcast_add", node.name + "_addbias", {},
                     [(deq, 0), bias_entry])

    return _graph_rewrite(symbol, hook)


def _attr_tuple(v):
    if isinstance(v, str):
        import ast
        return ast.literal_eval(v)
    return tuple(v) if not isinstance(v, int) else (v,)


def _elide_dq_q(symbol):
    """Fuse dequantize→quantize_v2 chains into requantize so adjacent int8
    layers hand tensors over without a round-trip through fp32
    (reference: quantize_graph_pass.cc requantize fusion)."""
    from ..symbol.symbol import _Node

    def hook(node, new, clone):
        if node.op != "_contrib_quantize_v2" or not node.inputs:
            return None
        src, _ = node.inputs[0]
        # only when the dequantize reads an int32 accumulator (conv/fc);
        # int8 producers (pooling) use a different scale domain
        acc_ok = src.inputs and src.inputs[0][0].op in (
            "_contrib_quantized_conv",
            "_contrib_quantized_fully_connected")
        if src.op != "_contrib_dequantize" or not acc_ok or \
                "min_calib_range" not in node.attrs:
            return None
        acc_entry = new.inputs[0][0].inputs  # dequantize's inputs
        return _Node("_contrib_requantize", node.name + "_rq",
                     {"min_calib_range": node.attrs["min_calib_range"],
                      "max_calib_range": node.attrs["max_calib_range"],
                      "out_type": node.attrs.get("out_type", "int8")},
                     list(acc_entry))

    return _graph_rewrite(symbol, hook)


def _amax_of(attrs):
    lo = float(_reg_canon(attrs["min_calib_range"]))
    hi = float(_reg_canon(attrs["max_calib_range"]))
    return max(abs(lo), abs(hi))


_CALIB_PRODUCERS = ("_contrib_quantize_v2", "_contrib_requantize",
                    "_contrib_quantized_conv_requant")


def _fuse_conv_requant(symbol, arg_params):
    """Fuse qconv → dequantize → [bias add] → [relu] → quantize chains into
    one ``_contrib_quantized_conv_requant`` node (reference:
    quantize_graph_pass.cc fusion; kernel: ops/pallas_kernels.py
    qmm_requant).  Only NHWC chains whose intermediates have exactly one
    consumer fuse; residual branches (dequantize feeding an fp32 add)
    stay unfused.  Opt-in via MXTPU_FUSE_QCONV=1 — measured slower than
    the split graph on v5e (docs/perf_resnet50_tpu.md r3)."""
    from ..symbol.symbol import _Node

    consumers = _consumer_sets(symbol)

    def single(node):
        return len(consumers.get(id(node), ())) == 1

    def hook(node, new, clone):
        if node.op != "_contrib_quantize_v2" or \
                "min_calib_range" not in node.attrs or not node.inputs:
            return None
        # walk up: [relu] <- [bias add] <- dequantize <- qconv
        cur = node.inputs[0][0]
        relu = False
        bias_node = None
        if cur.op == "Activation" and single(cur) and \
                _reg_canon(cur.attrs.get("act_type")) == "relu":
            relu = True
            cur = cur.inputs[0][0]
        if cur.op == "broadcast_add" and single(cur) and \
                cur.inputs[1][0].op is None:
            bias_node = cur.inputs[1][0]
            cur = cur.inputs[0][0]
        if cur.op != "_contrib_dequantize" or not single(cur):
            return None
        qconv = cur.inputs[0][0]
        if qconv.op != "_contrib_quantized_conv" or not single(qconv):
            return None
        if qconv.attrs.get("layout") not in ("NWC", "NHWC", "NDHWC"):
            return None
        qdata = qconv.inputs[0][0]
        if qdata.op not in _CALIB_PRODUCERS or \
                "min_calib_range" not in qdata.attrs:
            return None
        wq = qconv.inputs[1][0]
        if wq.op is not None or not wq.name.endswith("_quantized"):
            return None
        base = wq.name[:-len("_quantized")]
        if base + "_min" not in arg_params:
            return None
        w_amax = max(abs(float(arg_params[base + "_min"].asnumpy()[0])),
                     abs(float(arg_params[base + "_max"].asnumpy()[0])))
        attrs = {k: qconv.attrs[k] for k in _QCONV_ATTRS
                 if k in qconv.attrs}
        attrs.update({
            "in_scale": _amax_of(qdata.attrs) / 127.0,
            "w_scale": w_amax / 127.0,
            "out_scale": _amax_of(node.attrs) / 127.0,
            "relu": relu,
            "min_calib_range": node.attrs["min_calib_range"],
            "max_calib_range": node.attrs["max_calib_range"],
        })
        inputs = [(clone(qdata), 0), (clone(wq), 0)]
        if bias_node is not None:
            inputs.append((clone(bias_node), 0))
        return _Node("_contrib_quantized_conv_requant",
                     node.name + "_fused", attrs, inputs)

    return _graph_rewrite(symbol, hook)


_rewrite_int8_fc = _rewrite_int8  # back-compat name


def calib_graph(qsym, th_dict):
    """Attach calibrated thresholds as node attrs
    (reference: quantize_graph_pass.cc calibration)."""
    for node, _ in qsym.get_internals()._outputs:
        key = node.name + "_output"
        if key in th_dict:
            lo, hi = th_dict[key]
            node.attrs["__min_calib_range__"] = str(lo)
            node.attrs["__max_calib_range__"] = str(hi)
    return qsym


def quantize_model(sym_in, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", fold_bn=True, logger=logging):
    """Quantize weights to int8 and (optionally) calibrate activations
    (reference: contrib/quantization.py quantize_model).
    ``calib_mode``: "naive" (min/max) or "entropy" (KL-optimal thresholds,
    reference :253); ``fold_bn`` folds Convolution→BatchNorm chains into
    the conv weights first so the int8 convs carry their scale/shift as a
    fused epilogue instead of a separate fp32 BN pass.

    ``excluded_sym_names``: ops to keep on the float rail.  The reference
    excludes the stem conv (conv0) by default for accuracy
    (quantize_graph_pass.cc); here nothing is excluded implicitly — pass
    the stem name (or set ``MXTPU_INT8_EXCLUDE=name1,name2`` where a tool
    honors it, e.g. bench.py) to restore the reference's
    accuracy-motivated default, and validate any quantize-everything
    recipe with an accuracy gate (bench.py's ≤1% top-1 drop bound is the
    model to copy).

    Returns (symbol, qarg_params, aux_params): weights stored quantized as
    (int8 data, min, max) triples under their original names + suffixes."""
    excluded = set(excluded_sym_names or [])
    if fold_bn:
        sym_in, arg_params, aux_params = fold_batch_norms(
            sym_in, arg_params, aux_params)
    qarg_params = {}
    for name, arr in arg_params.items():
        layer = name[:-len("_weight")] if name.endswith("_weight") else name
        if name.endswith("weight") and layer not in excluded:
            q, mn, mx = nd.contrib.quantize_v2(arr, out_type=quantized_dtype)
            qarg_params[name + "_quantized"] = q
            qarg_params[name + "_min"] = mn
            qarg_params[name + "_max"] = mx
            # keep the fp32 copy too: ops without int8 kernels fall back
            qarg_params[name] = arr
        else:
            qarg_params[name] = arr

    th_dict = {}
    if calib_mode != "none" and calib_data is not None:
        th_dict = _collect_layer_stats(sym_in, arg_params, aux_params,
                                       calib_data, num_calib_examples,
                                       list(data_names), list(label_names))
        if calib_mode == "entropy":
            # KL-optimal clip thresholds (reference: calib_mode='entropy',
            # contrib/quantization.py:340) from a second histogram pass —
            # only over ranges a quantizable node will actually consume
            # (the KL search is host-side Python; running it for every
            # internal output would cost minutes on a deep net)
            needed = set()
            for node in sym_in._nodes():
                if node.op in _QUANTIZABLE and node.name not in excluded \
                        and node.inputs:
                    needed.add(_entry_range_key(node.inputs[0]))
            needed &= set(th_dict)
            sub_stats = {k: th_dict[k] for k in needed}
            if sub_stats:
                hists, edges = _collect_layer_histograms(
                    sym_in, arg_params, aux_params, calib_data,
                    num_calib_examples, list(data_names), sub_stats)
                for name in needed:
                    th = optimal_threshold(hists[name], edges[name])
                    th_dict[name] = (-th, th)
        logger.info("calibrated %d layer output ranges (%s)",
                    len(th_dict), calib_mode)
        sym_in = calib_graph(sym_in, th_dict)
        # rewrite calibrated FC/conv/pooling layers to real int8 subgraphs,
        # fuse dequantize->quantize handoffs into requantize, then fuse
        # whole qconv->bias->relu->quantize chains into single int8-out
        # nodes (Pallas MXU kernel for NHWC 1x1)
        sym_in = _rewrite_int8(sym_in, qarg_params, th_dict, excluded)
        sym_in = _elide_dq_q(sym_in)
        # opt-in: collapsing the whole qconv->bias->relu->quantize chain
        # into one node measured 25-40% SLOWER on v5e — XLA fuses the
        # epilogue INTO the conv and loses the conv's optimal tiling;
        # as separate HLOs the conv runs clean and the elementwise chain
        # is one fast standalone fusion (docs/perf_resnet50_tpu.md r3,
        # "levers measured and rejected")
        import os as _os
        if _os.environ.get("MXTPU_FUSE_QCONV", "0") == "1":
            sym_in = _fuse_conv_requant(sym_in, qarg_params)
    return sym_in, qarg_params, aux_params
