"""Model quantization driver (reference: python/mxnet/contrib/
quantization.py — quantize_model calibration flow over the int8 ops).

TPU-native: calibration collects per-layer min/max over a DataIter; the
returned (symbol, params) pair carries quantize_v2 nodes with calibrated
ranges, so inference runs int8 matmuls on the MXU.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from .. import symbol as sym

__all__ = ["quantize_model", "calib_graph"]


def _collect_layer_stats(symbol, arg_params, aux_params, calib_data,
                         num_calib_examples, data_names, label_names):
    """Run calibration batches through the fp32 graph collecting per-output
    min/max (reference: _collect_layer_output_min_max)."""
    from ..module.module import Module
    internals = symbol.get_internals()
    outputs = [o for o in internals.list_outputs()
               if o.endswith("_output") or o in data_names]
    group = sym.Group([internals[o] for o in outputs])
    mod = Module(group, data_names=data_names, label_names=None)
    mod.bind(calib_data.provide_data, for_training=False)
    mod.set_params(arg_params, aux_params, allow_missing=True,
                   allow_extra=True)
    stats = {o: (np.inf, -np.inf) for o in outputs}
    seen = 0
    calib_data.reset()
    for batch in calib_data:
        mod.forward(batch, is_train=False)
        for name, out in zip(outputs, mod.get_outputs()):
            a = out.asnumpy()
            lo, hi = stats[name]
            stats[name] = (min(lo, float(a.min())), max(hi, float(a.max())))
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return stats


def _entry_range_key(entry):
    node, _ = entry
    return node.name if node.op is None else node.name + "_output"


# attrs each quantized op inherits from its fp32 node
_QCONV_ATTRS = ("kernel", "stride", "dilate", "pad", "num_filter",
                "num_group", "layout")
_QPOOL_ATTRS = ("kernel", "pool_type", "global_pool", "pooling_convention",
                "stride", "pad", "count_include_pad", "layout")
_QUANTIZABLE = {"FullyConnected", "Convolution", "Pooling"}


def _rewrite_int8(symbol, arg_params, th_dict, excluded):
    """Replace calibrated FullyConnected/Convolution/Pooling nodes with
    quantize_v2 → quantized op → dequantize (+ fp32 bias) subgraphs — the
    quantize_graph_pass.cc analogue (reference also covers conv and
    pooling: quantized_conv.cu, quantized_pooling.cc).  Layers without a
    calibrated input range, or in `excluded`, stay fp32."""
    from ..symbol.symbol import Symbol, _Node

    memo = {}

    def clone(node):
        if id(node) in memo:
            return memo[id(node)]
        new = _Node(node.op, node.name, dict(node.attrs), [], node._is_aux)
        memo[id(node)] = new  # register before recursing into inputs
        new.inputs = [(clone(c), i) for c, i in node.inputs]
        if node.op not in _QUANTIZABLE or node.name in excluded:
            return new
        rng = th_dict.get(_entry_range_key(node.inputs[0]))
        if rng is None:
            return new
        lo, hi = rng
        data_entry = new.inputs[0]
        qdata = _Node("_contrib_quantize_v2", node.name + "_qdata",
                      {"out_type": "int8", "min_calib_range": lo,
                       "max_calib_range": hi}, [data_entry])

        if node.op == "Pooling":
            qpool = _Node("_contrib_quantized_pooling", node.name + "_int8",
                          {k: node.attrs[k] for k in _QPOOL_ATTRS
                           if k in node.attrs},
                          [(qdata, 0), (qdata, 1), (qdata, 2)])
            deq = _Node("_contrib_dequantize", node.name + "_deq", {},
                        [(qpool, 0), (qpool, 1), (qpool, 2)])
            memo[id(node)] = deq
            return deq

        wname = node.name + "_weight"
        if wname + "_quantized" not in arg_params:
            return new

        def qvar(suffix):
            full = wname + suffix
            arr = arg_params[full]
            return _Node(None, full,
                         {"__shape__": str(tuple(arr.shape)),
                          "__dtype__": str(np.dtype(arr.dtype).name)})

        wq = qvar("_quantized")
        wmn = qvar("_min")
        wmx = qvar("_max")
        has_bias = len(node.inputs) > 2
        if node.op == "FullyConnected":
            attrs = {"num_hidden": node.attrs.get("num_hidden"),
                     "no_bias": True,
                     "flatten": node.attrs.get("flatten", True)}
            qop_name = "_contrib_quantized_fully_connected"
        else:
            attrs = {k: node.attrs[k] for k in _QCONV_ATTRS
                     if k in node.attrs}
            attrs["no_bias"] = True
            qop_name = "_contrib_quantized_conv"
        qop = _Node(qop_name, node.name + "_int8", attrs,
                    [(qdata, 0), (wq, 0), (qdata, 1), (qdata, 2),
                     (wmn, 0), (wmx, 0)])
        deq = _Node("_contrib_dequantize", node.name + "_deq",
                    {}, [(qop, 0), (qop, 1), (qop, 2)])
        if has_bias:
            bias_entry = new.inputs[2]
            bname = node.name + "_bias"
            if bias_entry[0].op is None and bname in arg_params:
                # no fp32 node derives its shape anymore — pin it on the var
                bias_entry[0].attrs.setdefault(
                    "__shape__", str(tuple(arg_params[bname].shape)))
            if node.op == "Convolution":
                # bias broadcasts over channels: (C,) -> (1, C, 1, ...)
                nsp = len(_attr_tuple(node.attrs.get("kernel", (1, 1))))
                bshape = (1, -1) + (1,) * nsp
                bias_entry = (_Node("Reshape", node.name + "_bias_rs",
                                    {"shape": str(bshape)}, [bias_entry]), 0)
            out = _Node("broadcast_add", node.name + "_addbias", {},
                        [(deq, 0), bias_entry])
        else:
            out = deq
        memo[id(node)] = out
        return out

    return Symbol([(clone(n), i) for n, i in symbol._outputs])


def _attr_tuple(v):
    if isinstance(v, str):
        import ast
        return ast.literal_eval(v)
    return tuple(v) if not isinstance(v, int) else (v,)


def _elide_dq_q(symbol):
    """Fuse dequantize→quantize_v2 chains into requantize so adjacent int8
    layers hand tensors over without a round-trip through fp32
    (reference: quantize_graph_pass.cc requantize fusion)."""
    from ..symbol.symbol import Symbol, _Node

    memo = {}

    def clone(node):
        if id(node) in memo:
            return memo[id(node)]
        new = _Node(node.op, node.name, dict(node.attrs), [], node._is_aux)
        memo[id(node)] = new
        new.inputs = [(clone(c), i) for c, i in node.inputs]
        if node.op == "_contrib_quantize_v2" and node.inputs:
            src, _ = node.inputs[0]
            # only when the dequantize reads an int32 accumulator (conv/fc);
            # int8 producers (pooling) use a different scale domain
            acc_ok = src.inputs and src.inputs[0][0].op in (
                "_contrib_quantized_conv",
                "_contrib_quantized_fully_connected")
            if src.op == "_contrib_dequantize" and acc_ok and \
                    "min_calib_range" in node.attrs:
                acc_entry = new.inputs[0][0].inputs  # dequantize's inputs
                rq = _Node("_contrib_requantize", node.name + "_rq",
                           {"min_calib_range":
                            node.attrs["min_calib_range"],
                            "max_calib_range":
                            node.attrs["max_calib_range"],
                            "out_type": node.attrs.get("out_type", "int8")},
                           list(acc_entry))
                memo[id(node)] = rq
                return rq
        return new

    return Symbol([(clone(n), i) for n, i in symbol._outputs])


_rewrite_int8_fc = _rewrite_int8  # back-compat name


def calib_graph(qsym, th_dict):
    """Attach calibrated thresholds as node attrs
    (reference: quantize_graph_pass.cc calibration)."""
    for node, _ in qsym.get_internals()._outputs:
        key = node.name + "_output"
        if key in th_dict:
            lo, hi = th_dict[key]
            node.attrs["__min_calib_range__"] = str(lo)
            node.attrs["__max_calib_range__"] = str(hi)
    return qsym


def quantize_model(sym_in, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging):
    """Quantize weights to int8 and (optionally) calibrate activations
    (reference: contrib/quantization.py quantize_model).

    Returns (symbol, qarg_params, aux_params): weights stored quantized as
    (int8 data, min, max) triples under their original names + suffixes."""
    excluded = set(excluded_sym_names or [])
    qarg_params = {}
    for name, arr in arg_params.items():
        layer = name[:-len("_weight")] if name.endswith("_weight") else name
        if name.endswith("weight") and layer not in excluded:
            q, mn, mx = nd.contrib.quantize_v2(arr, out_type=quantized_dtype)
            qarg_params[name + "_quantized"] = q
            qarg_params[name + "_min"] = mn
            qarg_params[name + "_max"] = mx
            # keep the fp32 copy too: ops without int8 kernels fall back
            qarg_params[name] = arr
        else:
            qarg_params[name] = arr

    th_dict = {}
    if calib_mode != "none" and calib_data is not None:
        th_dict = _collect_layer_stats(sym_in, arg_params, aux_params,
                                       calib_data, num_calib_examples,
                                       list(data_names), list(label_names))
        logger.info("calibrated %d layer output ranges", len(th_dict))
        sym_in = calib_graph(sym_in, th_dict)
        # rewrite calibrated FC/conv/pooling layers to real int8 subgraphs,
        # then fuse dequantize->quantize handoffs into requantize
        sym_in = _rewrite_int8(sym_in, qarg_params, th_dict, excluded)
        sym_in = _elide_dq_q(sym_in)
    return sym_in, qarg_params, aux_params
