"""Text vocab + pretrained embeddings
(reference: python/mxnet/contrib/text/__init__.py — same submodule
layout: vocab, embedding, utils)."""
from . import embedding, utils, vocab
from .embedding import (CompositeEmbedding, CustomEmbedding, FastText,
                        GloVe)
from .utils import count_tokens_from_str
from .vocab import Vocabulary

__all__ = ["embedding", "utils", "vocab", "Vocabulary",
           "count_tokens_from_str", "CustomEmbedding", "GloVe",
           "FastText", "CompositeEmbedding"]
