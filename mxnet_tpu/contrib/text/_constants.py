"""Published pretrained-embedding catalogs
(reference: python/mxnet/contrib/text/_constants.py).

The SHA-1 values are the published checksums of the hosted GloVe /
fastText artifacts — factual catalog data the verification path needs.
The fastText wiki.* catalog lists ~300 languages upstream; this build
carries the headline entries in the same ``{file: sha1}`` format (extend
by adding entries, the loaders are format-agnostic).
"""

UNKNOWN_IDX = 0

# archives (what gets downloaded) -> sha1
GLOVE_ARCHIVE_SHA1 = {
    "glove.42B.300d.zip": "f8e722b39578f776927465b71b231bae2ae8776a",
    "glove.6B.zip": "b64e54f1877d2f735bdd000c1d7d771e25c7dfdc",
    "glove.840B.300d.zip": "8084fbacc2dee3b1fd1ca4cc534cbfff3519ed0d",
    "glove.twitter.27B.zip": "dce69c404025a8312c323197347695e81fd529fc",
}

# extracted text files (what gets loaded) -> sha1
GLOVE_FILE_SHA1 = {
    "glove.42B.300d.txt": "876767977d6bd4d947c0f84d44510677bc94612a",
    "glove.6B.50d.txt": "21bf566a9d27f84d253e0cd4d4be9dcc07976a6d",
    "glove.6B.100d.txt": "16b1dbfaf35476790bd9df40c83e2dfbd05312f1",
    "glove.6B.200d.txt": "17d0355ddaa253e298ede39877d1be70f99d9148",
    "glove.6B.300d.txt": "646443dd885090927f8215ecf7a677e9f703858d",
    "glove.840B.300d.txt": "294b9f37fa64cce31f9ebb409c266fc379527708",
    "glove.twitter.27B.25d.txt":
        "767d80889d8c8a22ae7cd25e09d0650a6ff0a502",
    "glove.twitter.27B.50d.txt":
        "9585f4be97e286339bf0112d0d3aa7c15a3e864d",
    "glove.twitter.27B.100d.txt":
        "1bbeab8323c72332bd46ada0fc3c99f2faaa8ca8",
    "glove.twitter.27B.200d.txt":
        "7921c77a53aa5977b1d9ce3a7c4430cbd9d1207a",
}

FAST_TEXT_FILE_SHA1 = {
    "crawl-300d-2M.vec": "9b556504d099a6c01f3dd76b88775d02cb2f1946",
    "wiki.en.vec": "c1e418f144ceb332b4328d27addf508731fa87df",
    "wiki.simple.vec": "55267c50fbdf4e4ae0fbbda5c73830a379d68795",
}

FAST_TEXT_ARCHIVE_SHA1 = {
    "crawl-300d-2M.zip": "bb40313d15837ceecc1e879bc954e9be04b17c3c",
    "wiki.en.zip": "7f83d578a31a8168423c77ea25ad381494a5e920",
    "wiki.simple.zip": "367737535e39defb0e713a7ff2374cb932c5a9bc",
}
