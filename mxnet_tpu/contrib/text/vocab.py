"""Indexed vocabulary (reference: python/mxnet/contrib/text/vocab.py)."""
from __future__ import annotations

from . import _constants as C


class Vocabulary:
    """Maps tokens to indices, index 0 reserved for ``unknown_token``,
    then any ``reserved_tokens``, then counter keys by descending
    frequency / ascending token (reference: vocab.py:33 Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("`min_freq` must be set to a positive value")
        if reserved_tokens is not None:
            reserved_set = set(reserved_tokens)
            if unknown_token in reserved_set:
                raise ValueError("`reserved_tokens` cannot contain "
                                 "`unknown_token`")
            if len(reserved_set) != len(reserved_tokens):
                raise ValueError("`reserved_tokens` cannot contain "
                                 "duplicate tokens")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) if reserved_tokens \
            else None
        self._idx_to_token = [unknown_token] + (self._reserved_tokens or [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda x: (-x[1], x[0]))
        if most_freq_count is not None:
            pairs = pairs[:most_freq_count]
        for token, freq in pairs:
            if freq < min_freq:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        out = [self._token_to_idx.get(t, C.UNKNOWN_IDX) for t in tokens]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        if single:
            indices = [indices]
        out = []
        for i in indices:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("token index %d out of range [0, %d)"
                                 % (i, len(self._idx_to_token)))
            out.append(self._idx_to_token[i])
        return out[0] if single else out
