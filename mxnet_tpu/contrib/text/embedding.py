"""Pretrained token embeddings: GloVe / fastText / custom / composite
(reference: python/mxnet/contrib/text/embedding.py).

Design notes (TPU build): the token->vector table is assembled host-side
in one numpy buffer and materialized as a single NDArray — embedding
lookup during data prep is host work; the device sees only the final
``idx_to_vec`` table (feed it to ``gluon.nn.Embedding.weight`` or
``nd.Embedding``).  Downloads ride gluon.utils.download (sha1-verified,
retried); ``file://`` repo URLs make the whole fetch+verify+extract path
unit-testable offline (MXNET_GLUON_REPO override, reference:
embedding.py:199 _get_pretrained_file).
"""
from __future__ import annotations

import io
import logging
import os
import tarfile
import warnings
import zipfile

import numpy as np

from ... import ndarray as nd
from ...base import Registry
from . import _constants as C
from . import vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

_REG = Registry("token embedding")


def register(embedding_cls):
    """Register a subclass of ``_TokenEmbedding`` for ``create``
    (reference: embedding.py:39)."""
    _REG.register(embedding_cls)
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding by name, e.g.
    ``create("glove", pretrained_file_name="glove.6B.50d.txt")``
    (reference: embedding.py:62)."""
    return _REG.create(embedding_name, **kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Catalog of pretrained files, per embedding or all
    (reference: embedding.py:89)."""
    if embedding_name is not None:
        cls = _REG.find(embedding_name)
        return list(cls.pretrained_file_name_sha1.keys())
    return {name: list(_REG.find(name).pretrained_file_name_sha1.keys())
            for name in _REG.keys()}


class _TokenEmbedding(vocab.Vocabulary):
    """Base: a Vocabulary whose indices also map to embedding vectors.

    Semantics kept from the reference (embedding.py:132):
    - index 0 (unknown) takes the file's ``unknown_token`` vector if the
      file has one, else ``init_unknown_vec``
    - first-encountered duplicate token wins; later ones are skipped
      with a warning
    - 1-dimensional rows (fastText headers) are skipped with a warning
    - with a ``vocabulary``, only its tokens get vectors
    """

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None
        self._table_np = None  # host mirror: lookups never re-copy HBM

    def _set_table(self, table_np):
        self._table_np = table_np
        self._idx_to_vec = nd.array(table_np)

    # -- acquisition -------------------------------------------------------
    @classmethod
    def _get_download_file_name(cls, pretrained_file_name):
        return pretrained_file_name

    @classmethod
    def _get_pretrained_file_url(cls, pretrained_file_name):
        from ...gluon.utils import get_repo_url
        return "{}gluon/embeddings/{}/{}".format(
            get_repo_url(), cls.__name__.lower(),
            cls._get_download_file_name(pretrained_file_name))

    @classmethod
    def _get_pretrained_file(cls, embedding_root, pretrained_file_name):
        """Resolve (download + sha1-verify + extract) a catalog file
        (reference: embedding.py:199)."""
        from ...gluon.utils import check_sha1, download
        embedding_root = os.path.expanduser(embedding_root)
        url = cls._get_pretrained_file_url(pretrained_file_name)
        embedding_dir = os.path.join(embedding_root, cls.__name__.lower())
        pretrained_file_path = os.path.join(embedding_dir,
                                            pretrained_file_name)
        downloaded_file = os.path.basename(url)
        downloaded_file_path = os.path.join(embedding_dir, downloaded_file)
        expected_file_hash = \
            cls.pretrained_file_name_sha1[pretrained_file_name]
        archive_sha1 = getattr(cls, "pretrained_archive_name_sha1", None)
        expected_download_hash = archive_sha1[downloaded_file] \
            if archive_sha1 else expected_file_hash
        if not os.path.exists(pretrained_file_path) \
                or not check_sha1(pretrained_file_path,
                                  expected_file_hash):
            download(url, downloaded_file_path,
                     sha1_hash=expected_download_hash)
            ext = os.path.splitext(downloaded_file)[1]
            if ext == ".zip":
                with zipfile.ZipFile(downloaded_file_path, "r") as zf:
                    zf.extractall(embedding_dir)
            elif ext == ".gz":
                with tarfile.open(downloaded_file_path, "r:gz") as tar:
                    tar.extractall(path=embedding_dir)
        return pretrained_file_path

    # -- loading -----------------------------------------------------------
    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise ValueError("`pretrained_file_path` must be a valid path "
                             "to the pre-trained token embedding file")
        logging.getLogger(__name__).info(
            "loading embedding vectors from %s", pretrained_file_path)
        vec_len = None
        rows = []
        seen = set()
        loaded_unknown_vec = None
        # indices below this (unknown + any reserved_tokens) already
        # exist in the vocabulary; file tokens append after them
        base = len(self._idx_to_token)
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f, 1):
                elems = line.rstrip().split(elem_delim)
                if len(elems) <= 1:
                    raise ValueError(
                        "line %d of %s: unexpected data format"
                        % (line_num, pretrained_file_path))
                token, values = elems[0], elems[1:]
                if token == self.unknown_token and \
                        loaded_unknown_vec is None:
                    loaded_unknown_vec = np.asarray(values, np.float32)
                    seen.add(token)
                elif token in seen:
                    warnings.warn(
                        "line %d: duplicate embedding for token %r "
                        "skipped (first occurrence wins)"
                        % (line_num, token))
                elif len(values) == 1:
                    warnings.warn("line %d: token %r with 1-dimensional "
                                  "vector %r is likely a header, skipped"
                                  % (line_num, token, values))
                else:
                    if vec_len is None:
                        vec_len = len(values)
                    elif len(values) != vec_len:
                        raise ValueError(
                            "line %d: dimension %d != previous dimension "
                            "%d; all vectors must agree"
                            % (line_num, len(values), vec_len))
                    rows.append(np.asarray(values, np.float32))
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = len(self._idx_to_token) - 1
                    seen.add(token)
        if vec_len is None:
            raise ValueError("no embedding vectors loaded from %s"
                             % pretrained_file_path)
        self._vec_len = vec_len
        table = np.empty((base + len(rows), vec_len), np.float32)
        # unknown + reserved tokens all take the init vector (the
        # reference docstring's "initialized embedding vector for every
        # reserved token"); a file-provided <unk> row overrides index 0
        table[:base] = init_unknown_vec(shape=vec_len).asnumpy()
        if loaded_unknown_vec is not None:
            table[C.UNKNOWN_IDX] = loaded_unknown_vec
        if rows:
            table[base:] = np.stack(rows)
        self._set_table(table)

    def _index_tokens_from_vocabulary(self, vocabulary):
        self._token_to_idx = vocabulary.token_to_idx.copy() \
            if vocabulary.token_to_idx is not None else None
        self._idx_to_token = vocabulary.idx_to_token[:] \
            if vocabulary.idx_to_token is not None else None
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens[:] \
            if vocabulary.reserved_tokens is not None else None

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len,
                                      vocab_idx_to_token):
        """Concatenate per-token vectors from one or more embeddings
        into this instance's table (reference: embedding.py:313)."""
        new_vec_len = sum(e.vec_len for e in token_embeddings)
        table = np.zeros((vocab_len, new_vec_len), np.float32)
        col = 0
        for emb in token_embeddings:
            end = col + emb.vec_len
            table[0, col:end] = emb.idx_to_vec[C.UNKNOWN_IDX].asnumpy()
            if vocab_len > 1:
                table[1:, col:end] = emb.get_vecs_by_tokens(
                    vocab_idx_to_token[1:]).asnumpy()
            col = end
        self._vec_len = new_vec_len
        self._set_table(table)

    def _build_embedding_for_vocabulary(self, vocabulary):
        if vocabulary is not None:
            if not isinstance(vocabulary, vocab.Vocabulary):
                raise TypeError("`vocabulary` must be a "
                                "contrib.text.vocab.Vocabulary")
            self._set_idx_to_vec_by_embeddings(
                [self], len(vocabulary), vocabulary.idx_to_token)
            self._index_tokens_from_vocabulary(vocabulary)

    # -- access ------------------------------------------------------------
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get the index-0 vector
        (reference: embedding.py:365)."""
        single = not isinstance(tokens, list)
        if single:
            tokens = [tokens]
        if not lower_case_backup:
            indices = [self.token_to_idx.get(t, C.UNKNOWN_IDX)
                       for t in tokens]
        else:
            indices = [self.token_to_idx[t] if t in self.token_to_idx
                       else self.token_to_idx.get(t.lower(), C.UNKNOWN_IDX)
                       for t in tokens]
        vecs = nd.array(self._table_np[np.asarray(indices)])
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors for known tokens; unknown tokens error so a
        typo can't silently write the wrong row (reference:
        embedding.py:404)."""
        if self._idx_to_vec is None:
            raise ValueError("`idx_to_vec` has not been set")
        single = not isinstance(tokens, list)
        if single:
            tokens = [tokens]
        arr = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors, np.float32)
        if arr.ndim == 1:
            arr = arr[None]
        if arr.shape != (len(tokens), self.vec_len):
            raise ValueError(
                "new_vectors shape %s must be (%d, %d)"
                % (arr.shape, len(tokens), self.vec_len))
        indices = []
        for token in tokens:
            if token in self.token_to_idx:
                indices.append(self.token_to_idx[token])
            else:
                raise ValueError(
                    "token %r is unknown; to update the unknown vector, "
                    "name the unknown token %r explicitly"
                    % (token, self.idx_to_token[C.UNKNOWN_IDX]))
        # functional update, jax-style: rebuild the device table once
        table = np.array(self._table_np)
        table[np.asarray(indices)] = arr
        self._set_table(table)

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if pretrained_file_name not in cls.pretrained_file_name_sha1:
            raise KeyError(
                "cannot find pretrained file %s for embedding %s; valid "
                "files: %s" % (pretrained_file_name, cls.__name__.lower(),
                               ", ".join(cls.pretrained_file_name_sha1)))


# public alias for subclassing custom embeddings (the reference keeps the
# base private but registers subclasses of it; exposing the alias lets
# user code @register its own without reaching into privates)
TokenEmbedding = _TokenEmbedding


@register
class GloVe(_TokenEmbedding):
    """GloVe embeddings (reference: embedding.py:468).  Files extract
    from family zips; both are sha1-checked."""

    pretrained_archive_name_sha1 = C.GLOVE_ARCHIVE_SHA1
    pretrained_file_name_sha1 = C.GLOVE_FILE_SHA1

    @classmethod
    def _get_download_file_name(cls, pretrained_file_name):
        # glove.6B.50d.txt -> glove.6B.zip (the family archive)
        src = {a.split(".")[1]: a
               for a in cls.pretrained_archive_name_sha1}
        return src[pretrained_file_name.split(".")[1]]

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=os.path.join("~", ".mxnet_tpu",
                                             "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        GloVe._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = GloVe._get_pretrained_file(embedding_root,
                                          pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(_TokenEmbedding):
    """fastText .vec embeddings (reference: embedding.py:558); the .vec
    header row is auto-skipped by the 1-dimensional-row rule."""

    pretrained_archive_name_sha1 = C.FAST_TEXT_ARCHIVE_SHA1
    pretrained_file_name_sha1 = C.FAST_TEXT_FILE_SHA1

    @classmethod
    def _get_download_file_name(cls, pretrained_file_name):
        return ".".join(pretrained_file_name.split(".")[:-1]) + ".zip"

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join("~", ".mxnet_tpu",
                                             "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        FastText._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = FastText._get_pretrained_file(embedding_root,
                                             pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


class CustomEmbedding(_TokenEmbedding):
    """Embedding from a user file of ``token<delim>v1<delim>...``
    (reference: embedding.py:658)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        self._build_embedding_for_vocabulary(vocabulary)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenate one or more embeddings over a vocabulary's tokens
    (reference: embedding.py:719)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, vocab.Vocabulary):
            raise TypeError("`vocabulary` must be a "
                            "contrib.text.vocab.Vocabulary")
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        for emb in token_embeddings:
            if not isinstance(emb, _TokenEmbedding):
                raise TypeError("`token_embeddings` must be "
                                "_TokenEmbedding instance(s)")
        self._index_tokens_from_vocabulary(vocabulary)
        self._set_idx_to_vec_by_embeddings(
            token_embeddings, len(self), self.idx_to_token)
