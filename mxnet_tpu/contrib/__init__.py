"""`mx.contrib` (reference: python/mxnet/contrib/)."""
from . import text
from . import io
from . import autograd
from . import quantization
from . import tensorboard  # module import is safe; SummaryWriter is gated

__all__ = ["text", "io", "autograd", "quantization", "tensorboard"]
