"""`mx.contrib` (reference: python/mxnet/contrib/)."""
from . import text
from . import io
from . import autograd
from . import quantization

# tensorboard is import-gated (optional dependency)
__all__ = ["text", "io", "autograd", "quantization", "tensorboard"]
