"""Old contrib autograd API (reference: python/mxnet/contrib/autograd.py)
— thin shims over the modern mx.autograd."""
from __future__ import annotations

from .. import autograd as _ag

__all__ = ["set_is_training", "train_section", "test_section",
           "backward", "grad_and_loss", "grad"]


def set_is_training(is_train):
    prev = _ag.is_training()
    _ag.set_training(is_train)
    return prev


train_section = _ag.record
test_section = _ag.pause


def backward(outputs, out_grads=None, retain_graph=False):
    _ag.backward(outputs, head_grads=out_grads, retain_graph=retain_graph)


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient and loss
    (reference: contrib/autograd.py grad_and_loss)."""
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            idx = argnum if isinstance(argnum, (list, tuple)) else [argnum]
            variables = [args[i] for i in idx]
        for x in variables:
            x.attach_grad()
        with _ag.record():
            outputs = func(*args)
        _ag.backward([outputs] if not isinstance(outputs, (list, tuple))
                     else list(outputs))
        grads = [x.grad for x in variables]
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    def wrapped(*args):
        return grad_and_loss(func, argnum)(*args)[0]
    return wrapped
