"""Text utilities: vocabulary + embeddings
(reference: python/mxnet/contrib/text/ — vocab.py, embedding.py, utils.py).
"""
from __future__ import annotations

import collections
import os
import re

import numpy as np

from .. import ndarray as nd

__all__ = ["count_tokens_from_str", "Vocabulary", "CustomEmbedding"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency counter (reference: text/utils.py)."""
    source_str = re.split(token_delim + "|" + seq_delim, source_str)
    tokens = [t for t in source_str if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter


class Vocabulary:
    """Indexed vocabulary (reference: text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) if reserved_tokens \
            else None
        self._idx_to_token = [unknown_token] + (self._reserved_tokens or [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda x: (-x[1], x[0]))
        if most_freq_count is not None:
            pairs = pairs[:most_freq_count]
        for token, freq in pairs:
            if freq < min_freq:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        out = [self._token_to_idx.get(t, 0) for t in tokens]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        if single:
            indices = [indices]
        out = [self._idx_to_token[i] for i in indices]
        return out[0] if single else out


class CustomEmbedding:
    """Token embedding from a local pretrained file
    (reference: text/embedding.py CustomEmbedding; the hosted
    GloVe/fastText downloads need egress — load files explicitly)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 vocabulary=None):
        tokens = []
        vecs = []
        with open(pretrained_file_path) as f:
            for line in f:
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                tokens.append(parts[0])
                vecs.append([float(x) for x in parts[1:]])
        self._vec_len = len(vecs[0]) if vecs else 0
        mat = np.asarray(vecs, np.float32)
        self._token_to_vec = dict(zip(tokens, mat))
        if vocabulary is not None:
            self._vocab = vocabulary
        else:
            counter = collections.Counter(tokens)
            self._vocab = Vocabulary(counter, min_freq=1)
        table = np.zeros((len(self._vocab), self._vec_len), np.float32)
        for tok, vec in self._token_to_vec.items():
            idx = self._vocab.token_to_idx.get(tok)
            if idx is not None:
                table[idx] = vec
        self._idx_to_vec = nd.array(table)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        rows = []
        for t in tokens:
            v = self._token_to_vec.get(t)
            if v is None and lower_case_backup:
                v = self._token_to_vec.get(t.lower())
            rows.append(v if v is not None
                        else np.zeros(self._vec_len, np.float32))
        out = nd.array(np.stack(rows))
        return out[0] if single else out
