"""contrib IO helpers (reference: python/mxnet/contrib/io.py —
DataLoaderIter wraps a gluon DataLoader as a DataIter for Module code)."""
from __future__ import annotations

import numpy as np

from ..io import DataBatch, DataDesc, DataIter

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        sampler = getattr(loader, "_batch_sampler", None)
        super().__init__(
            batch_size=getattr(sampler, "_batch_size", 0) if sampler else 0)
        self._loader = loader
        self._iter = iter(loader)
        self._data_name = data_name
        self._label_name = label_name
        self._first = None
        try:
            self._first = next(self._iter)
        except StopIteration:
            pass

    @property
    def provide_data(self):
        if self._first is None:
            return []
        d = self._first[0] if isinstance(self._first, (list, tuple)) \
            else self._first
        return [DataDesc(self._data_name, d.shape, d.dtype)]

    @property
    def provide_label(self):
        if self._first is None or not isinstance(self._first, (list, tuple)) \
                or len(self._first) < 2:
            return []
        lbl = self._first[1]
        return [DataDesc(self._label_name, lbl.shape, lbl.dtype)]

    def reset(self):
        self._iter = iter(self._loader)
        self._first = None

    def next(self):
        if self._first is not None:
            batch, self._first = self._first, None
        else:
            batch = next(self._iter)
        if isinstance(batch, (list, tuple)):
            data, label = batch[0], batch[1] if len(batch) > 1 else None
        else:
            data, label = batch, None
        return DataBatch([data], [label] if label is not None else None,
                         pad=0)
