"""KVStore server entry point (reference: python/mxnet/kvstore_server.py —
the process ps-lite spawns with DMLC_ROLE=server running the optimizer).

TPU-native: the synchronous types need no server — push() applies the
optimizer against stored weights in-process and multi-host reduction is a
mesh psum (kvstore.py).  Two server shapes remain:

- ``dist_async``'s rank-0-embedded ``PSServer`` thread (kvstore.py
  ``_start_ps``) — the common case;
- a **standalone** PS process for launchers that spawn a dedicated
  server rank: ``DMLC_ROLE=server`` + ``MXTPU_PS_PORT`` makes
  :func:`_init_kvstore_server_module` host a ``PSServer`` with the full
  elasticity tier armed (heartbeat watchdog, dead-worker key
  reassignment, bounded staleness — docs/resilience.md) and block until
  SIGTERM/SIGINT.  The legacy probe surface is preserved: a
  server/scheduler role with only ``DMLC_PS_ROOT_URI`` set still exits
  immediately (the collective types have nothing for it to do)."""
from __future__ import annotations

import os
import signal
import sys
import threading

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


def _elasticity_env():
    """(heartbeat_timeout_s, max_staleness) from the MXTPU_* env knobs —
    the same knobs kvstore.py's embedded server reads."""
    hb_interval = float(os.environ.get("MXTPU_HEARTBEAT_INTERVAL_S", "2.0"))
    hb_timeout = float(os.environ.get("MXTPU_HEARTBEAT_TIMEOUT_S",
                                      str(hb_interval * 5)))
    staleness = os.environ.get("MXTPU_MAX_STALENESS")
    return (hb_timeout if hb_interval > 0 else None,
            int(staleness) if staleness else None)


def _durability_env():
    """(state_dir, snapshot_every, snapshot_keep) from the MXTPU_* env
    knobs.  With a state dir but no explicit cadence, snapshot every 100
    applied pushes — the WAL between snapshots stays a few MB for
    typical keys and replay is milliseconds."""
    state_dir = os.environ.get("MXTPU_PS_STATE_DIR") or None
    every = os.environ.get("MXTPU_PS_SNAPSHOT_EVERY")
    keep = int(os.environ.get("MXTPU_PS_SNAPSHOT_KEEP", "3"))
    if every:
        every = int(every)
    else:
        every = 100 if state_dir else None
    return state_dir, every, keep


def _serve_ps(port, num_workers):
    """Host a standalone PSServer until SIGTERM/SIGINT.

    The wait loop is bounded (Event.wait with a timeout — the SRC005
    discipline), so a missed signal can never wedge the process beyond
    one poll interval after ``stop`` is set some other way.  Shutdown is
    graceful: the signal flushes one final snapshot before exit, so a
    drained server never leans on WAL replay — and a SIGKILLed one
    recovers through it (``MXTPU_CHAOS`` faults are armed here so the
    chaos harness can schedule exactly that kill deterministically)."""
    from . import kvstore_ps
    from . import telemetry as _tele
    from .resilience import chaos as _chaos
    _chaos.install_from_env()
    # flight recorder + trace correlation armed from the launcher's env
    # (MXTPU_TELEMETRY_DIR): a SIGKILLed server leaves its last applied
    # (rank, push_step) story in the mmap ring for the postmortem CLI
    _tele.maybe_enable_from_env()
    hb_timeout, max_staleness = _elasticity_env()
    state_dir, snapshot_every, keep = _durability_env()
    server = kvstore_ps.PSServer(port=port, num_workers=num_workers,
                                 heartbeat_timeout_s=hb_timeout,
                                 max_staleness=max_staleness,
                                 state_dir=state_dir,
                                 snapshot_every=snapshot_every,
                                 snapshot_keep=keep)
    print("mxnet_tpu: standalone PS serving on port %d "
          "(workers=%d, heartbeat_timeout=%s, max_staleness=%s, "
          "state_dir=%s, generation=%d, recovered_wal=%d)"
          % (server.port, num_workers, hb_timeout, max_staleness,
             state_dir, server.generation, server.recovered_wal_records),
          file=sys.stderr)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # non-main thread (tests)
            break
    while not stop.wait(0.5):
        pass
    server.stop(final_snapshot=True)


class KVStoreServer:
    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handle = kvstore

    def run(self):
        """Host the standalone PS when the launcher env asks for one;
        otherwise return immediately (collectives have no server loop)."""
        port = int(os.environ.get("MXTPU_PS_PORT", "0"))
        if os.environ.get("DMLC_ROLE") == "server" and port:
            _serve_ps(port, int(os.environ.get("DMLC_NUM_WORKER", "1")))
        return


def _init_kvstore_server_module():
    """Explicit entry for launcher scripts (NOT run at import — a stray
    exported DMLC_ROLE must not kill every `import mxnet_tpu`).

    - role=server + MXTPU_PS_PORT: host the standalone elastic PS until
      signalled, then exit 0;
    - role=server/scheduler + DMLC_PS_ROOT_URI (legacy ps-lite spawn):
      nothing to do, exit 0."""
    role = os.environ.get("DMLC_ROLE", "worker")
    port = int(os.environ.get("MXTPU_PS_PORT", "0"))
    if role == "server" and port:
        _serve_ps(port, int(os.environ.get("DMLC_NUM_WORKER", "1")))
        sys.exit(0)
    if role in ("server", "scheduler") and os.environ.get("DMLC_PS_ROOT_URI"):
        print("mxnet_tpu: '%s' role has no work (the parameter server "
              "collapsed into mesh collectives); exiting" % role,
              file=sys.stderr)
        sys.exit(0)
