"""KVStore server entry point (reference: python/mxnet/kvstore_server.py —
the process ps-lite spawns with DMLC_ROLE=server running the optimizer).

TPU-native: there is no separate server process — push() applies the
optimizer against the stored weights in-process and multi-host reduction
is a mesh psum (see kvstore.py).  This module keeps the reference's entry
surface so launcher scripts that probe DMLC_ROLE keep working: a 'server'
or 'scheduler' role simply has nothing to do and returns."""
from __future__ import annotations

import os
import sys

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handle = kvstore

    def run(self):
        """The reference blocks in the ps-lite event loop; collectives have
        no server loop — return immediately."""
        return


def _init_kvstore_server_module():
    """Explicit entry for launcher scripts (NOT run at import — a stray
    exported DMLC_ROLE must not kill every `import mxnet_tpu`).  Exits only
    when the process is clearly a ps-lite-style server spawn: role is
    server/scheduler AND a tracker address is configured."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role in ("server", "scheduler") and os.environ.get("DMLC_PS_ROOT_URI"):
        print("mxnet_tpu: '%s' role has no work (the parameter server "
              "collapsed into mesh collectives); exiting" % role,
              file=sys.stderr)
        sys.exit(0)
