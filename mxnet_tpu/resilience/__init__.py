"""mxnet_tpu.resilience — the fault-tolerance substrate.

The reference stack inherits worker/server fault tolerance from ps-lite
(MXNet, arxiv 1512.01274 §4) and TensorFlow treats checkpoint-based
recovery as a first-class system property (arxiv 1605.08695); this
package is the reproduction's equivalent tier, built as four cooperating
pieces (see ``docs/resilience.md``):

- :mod:`.chaos` — deterministic fault injection: a seeded schedule of
  faults (kill/raise/delay/call) replayed at named probe sites, so every
  failure mode gets a reproducible tier-1 test;
- :mod:`.checkpoint` — atomic write-rename snapshots (params + optimizer
  state + RNG + iterator cursor) behind
  ``DataParallelTrainer.fit(checkpoint_dir=..., resume=True)``, with
  bitwise-identical post-crash replay;
- :mod:`.heartbeat` — worker heartbeats + a server-side watchdog, the
  liveness layer under ``kvstore_ps``'s elastic PS tier (dead-worker key
  reassignment, bounded-staleness rejoin);
- :mod:`.server_state` — durable PS server state: atomic snapshots (the
  checkpoint discipline) + a write-ahead log of applied pushes, so a
  SIGKILLed parameter server recovers to its exact pre-crash state and
  the fleet self-heals around the failover (generation handshake);
- :mod:`.backoff` — the one shared exponential-backoff-with-jitter
  retry policy (bench backend acquisition, launcher rank restarts,
  kvstore RPC reconnects).

``python -m mxnet_tpu.resilience.bench`` is the host-only proof harness:
it reports ``recovery_time_s``/``checkpoint_overhead_pct`` plus the PS
tier's ``server_recovery_time_s``/``wal_replay_rate_keys_per_s`` and
stays live when the TPU backend is down (the r05 bench pattern).
"""
from __future__ import annotations

from . import backoff, chaos, checkpoint, heartbeat, server_state, \
    supervisor
from .backoff import BackoffPolicy, RetriesExhausted, retry_call
from .chaos import (ChaosError, ChaosSchedule, Fault, install,
                    install_from_env, maybe_inject, triggered, uninstall)
from .checkpoint import (ShardIntegrityError, latest_checkpoint,
                         latest_sharded_checkpoint, list_checkpoints,
                         load_checkpoint, load_sharded_checkpoint,
                         save_checkpoint, save_sharded_checkpoint)
from .heartbeat import HeartbeatMonitor, HeartbeatSender
from .server_state import ServerStateStore
from .supervisor import ElasticSupervisor, SupervisorHalted

__all__ = [
    "backoff", "chaos", "checkpoint", "heartbeat", "server_state",
    "supervisor",
    "BackoffPolicy", "RetriesExhausted", "retry_call",
    "ChaosError", "ChaosSchedule", "Fault", "install", "install_from_env",
    "maybe_inject", "triggered", "uninstall",
    "save_checkpoint", "load_checkpoint", "latest_checkpoint",
    "list_checkpoints", "save_sharded_checkpoint",
    "load_sharded_checkpoint", "latest_sharded_checkpoint",
    "ShardIntegrityError",
    "HeartbeatMonitor", "HeartbeatSender", "ServerStateStore",
    "ElasticSupervisor", "SupervisorHalted",
]
