"""Deterministic fault injection: replay a seeded fault schedule.

Every failure mode this repo has met in production-shaped form — a
SIGKILLed pipeline worker, a dropped/delayed kvstore push, a stalled
backend init (BENCH_r03..r05), an overloaded serving queue — gets a
*reproducible* tier-1 test instead of a flaky prod story.  The pieces:

- **probe sites**: code at failure-relevant points calls
  ``chaos.maybe_inject("site.name", count, ctx=...)``.  When no schedule
  is installed this is one module-global ``None`` check — zero overhead
  in production.  Shipped sites: ``trainer.step`` (count = step number),
  ``pipeline.dispatch`` (count = batch index, ctx = the iterator),
  ``kvstore.request`` (count = request number, ctx = message tuple),
  ``kvstore.server_apply`` (count = applied-push ordinal on the PS
  server, ctx = (rank, step, key) — the SIGKILL-the-server site),
  ``kvstore.snapshot`` (server snapshot write), ``serving.batch``
  (count = batch number; a ``delay`` here is the runner-stall /
  queue-overload injection), ``serving.route`` (count = routed-request
  ordinal on the model fleet, ctx = (model, tier)), ``serving.swap``
  (fleet hot swap, ctx = model name), ``engine.flush``, ``backend.init``
  (bench.py acquisition attempts), ``checkpoint.save`` (mid-write, for
  atomicity tests).
- **faults**: ``Fault(site, at, action, arg)`` — trigger the ``at``-th
  probe hit (1-based; or the probe's explicit ``count``) at ``site`` and
  perform ``action``:

  =========  ==========================================================
  action     effect
  =========  ==========================================================
  raise      raise ``arg`` (an exception instance/class; default
             ``ChaosError``) out of the probe site
  delay      ``time.sleep(arg)`` seconds (stall injection)
  kill       ``os.kill(os.getpid(), SIGKILL)`` — the hard-crash case
  call       ``arg(ctx)`` — site-specific sabotage (e.g. SIGKILL a
             pipeline worker process through ``ctx``)
  =========  ==========================================================

- **schedules**: an explicit ``ChaosSchedule([Fault, ...])``, a seeded
  one (``ChaosSchedule.seeded`` — same seed, same schedule, forever), or
  ``install_from_env()`` parsing ``MXTPU_CHAOS="site:at:action[:arg]"``
  so a *subprocess* under test can be armed from its parent.

Faults fire once each (``repeat=True`` re-arms).  ``triggered()`` lists
what actually fired, for assertions.
"""
from __future__ import annotations

import os
import signal
import time

__all__ = ["Fault", "ChaosSchedule", "ChaosError", "install", "uninstall",
           "installed", "maybe_inject", "triggered", "install_from_env",
           "SITES"]

# The registered fault model: every probe site shipped in mxnet_tpu/ with
# a one-line contract.  This dict is the source of truth the TEL001 lint
# checks BOTH ways against the code and docs/observability.md — a probe
# site used but not registered here, or registered but never probed, is
# silent drift between the fault model and the trace and fails
# ``--self-check``.  Every fault that fires at any of these sites is
# stamped as a telemetry instant event + flight-ring record by
# ``maybe_inject`` (see ``telemetry.fault_event``) before its action
# runs, so even a ``kill`` leaves the evidence behind.
SITES = {
    "trainer.step": "count = trainer step number; fires before dispatch",
    "pipeline.dispatch": "per dispatched batch; ctx = (iter, wid, idx)",
    "kvstore.request": "per client RPC; ctx = the message tuple",
    "kvstore.server_apply": "count = applied-push ordinal on the PS "
                            "server; ctx = (rank, step, key)",
    "kvstore.snapshot": "PS server snapshot capture",
    "serving.batch": "count = batch number; delay = runner stall",
    "serving.route": "count = routed-request ordinal; ctx = (model, tier)",
    "serving.swap": "fleet hot swap; ctx = model name",
    "mlops.decision": "count = promotion evaluate tick; "
                      "ctx = (model, state)",
    "engine.flush": "run-ahead ring drain",
    "backend.init": "count = bench.py acquisition attempt",
    "checkpoint.save": "mid-checkpoint-write (atomicity tests)",
    "ckpt.shard_write": "before each shard install of a shard-parallel "
                        "snapshot; ctx = (step, rank)",
    "train.step": "elastic worker per-rank step probe "
                  "(tools/train_elastic.py); count = (step-1)*world + "
                  "rank position + 1; ctx = (rank, step)",
    "supervisor.decision": "before each elastic-supervisor decision "
                           "commit; count = decision seq; ctx = the "
                           "decision dict",
}


class ChaosError(RuntimeError):
    """Default injected failure (the 'dropped RPC' stand-in)."""


class Fault:
    """One scheduled fault: at the ``at``-th hit of ``site``, do ``action``."""

    __slots__ = ("site", "at", "action", "arg", "repeat", "_armed")

    def __init__(self, site, at, action="raise", arg=None, repeat=False):
        if action not in ("raise", "delay", "kill", "call"):
            raise ValueError("unknown chaos action %r" % (action,))
        self.site = str(site)
        self.at = int(at)
        self.action = action
        self.arg = arg
        self.repeat = bool(repeat)
        self._armed = True

    def spec(self):
        return (self.site, self.at, self.action, self.arg)

    def __repr__(self):
        return "Fault(%s@%d:%s)" % (self.site, self.at, self.action)


class ChaosSchedule:
    """An ordered set of faults plus per-site hit counters."""

    def __init__(self, faults=()):
        self.faults = list(faults)
        self._hits = {}
        self._triggered = []

    @classmethod
    def seeded(cls, seed, sites, n_faults=3, max_at=50, action="raise",
               arg=None):
        """Deterministic random schedule: ``n_faults`` faults spread over
        ``sites`` with hit indices in [1, max_at], fully determined by
        ``seed`` (same seed -> byte-identical schedule — the property
        tests/test_resilience.py pins)."""
        import random as _random
        rng = _random.Random(int(seed))
        sites = list(sites)
        faults = [Fault(sites[rng.randrange(len(sites))],
                        rng.randint(1, int(max_at)), action, arg)
                  for _ in range(int(n_faults))]
        return cls(faults)

    def specs(self):
        return [f.spec() for f in self.faults]

    def hits(self, site):
        return self._hits.get(site, 0)


_active = None  # the installed ChaosSchedule, or None (the fast path)


def install(schedule):
    """Install a schedule (replacing any active one); returns it."""
    global _active
    if isinstance(schedule, (list, tuple)):
        schedule = ChaosSchedule(schedule)
    _active = schedule
    return schedule


def uninstall():
    """Deactivate fault injection; returns the previous schedule."""
    global _active
    prev, _active = _active, None
    return prev


def installed():
    return _active


def triggered():
    """Specs of faults that actually fired (empty when inactive)."""
    return list(_active._triggered) if _active is not None else []


def maybe_inject(site, count=None, ctx=None):
    """Probe: called from instrumented sites.  No-op (one ``None`` check)
    unless a schedule is installed.  ``count`` overrides the internal
    per-site hit counter (e.g. the trainer passes its step number so the
    schedule is phrased in steps, not probe executions)."""
    sched = _active
    if sched is None:
        return
    if count is None:
        count = sched._hits[site] = sched._hits.get(site, 0) + 1
    else:
        sched._hits[site] = int(count)
    for f in sched.faults:
        if not f._armed or f.site != site or int(count) != f.at:
            continue
        if not f.repeat:
            f._armed = False
        sched._triggered.append(f.spec())
        # stamp the injection BEFORE the action runs: the flight-ring
        # record and trace instant survive even a SIGKILL action, which
        # is exactly when the evidence matters (lazy import: chaos stays
        # importable before the package finishes initializing)
        try:
            from .. import telemetry as _tele
            _tele.fault_event(site, f.at, f.action, ctx=ctx)
        except Exception:
            pass  # telemetry must never mask or reorder the fault itself
        if f.action == "delay":
            time.sleep(float(f.arg or 0.05))
        elif f.action == "kill":
            os.kill(int(f.arg) if f.arg else os.getpid(), signal.SIGKILL)
        elif f.action == "call":
            f.arg(ctx)
        else:  # raise
            exc = f.arg if f.arg is not None else ChaosError(
                "chaos: injected failure at %s hit %d" % (site, f.at))
            if isinstance(exc, type):
                exc = exc("chaos: injected failure at %s hit %d"
                          % (site, f.at))
            raise exc


def install_from_env(var="MXTPU_CHAOS"):
    """Arm faults from an env spec — the subprocess chaos hook.

    Format: comma-separated ``site:at:action[:arg]`` entries, e.g.
    ``MXTPU_CHAOS="trainer.step:7:kill"`` or
    ``"kvstore.request:3:raise,kvstore.request:5:delay:0.2"``.
    Returns the installed schedule, or None when the var is unset/empty.
    """
    spec = os.environ.get(var, "").strip()
    if not spec:
        return None
    faults = []
    for entry in spec.split(","):
        parts = entry.strip().split(":")
        if len(parts) < 3:
            raise ValueError("bad %s entry %r (want site:at:action[:arg])"
                             % (var, entry))
        site, at, action = parts[0], int(parts[1]), parts[2]
        arg = None
        if len(parts) > 3 and parts[3]:
            arg = float(parts[3]) if action == "delay" else parts[3]
        faults.append(Fault(site, at, action, arg))
    return install(ChaosSchedule(faults))
