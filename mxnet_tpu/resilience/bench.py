"""Host-only resilience micro-bench: ``python -m mxnet_tpu.resilience.bench``.

Run by ``bench.py``'s ``resilience`` stage as a ``JAX_PLATFORMS=cpu``
subprocess BEFORE backend acquisition (the r05 pattern), so the numbers
stay live when the TPU backend is down.  Prints ONE JSON line:

- ``resilience_checkpoint_overhead_pct`` — extra wall time of a training
  loop that auto-checkpoints at the default cadence
  (``DEFAULT_CHECKPOINT_EVERY``) vs the same loop without; the
  acceptance gate is < 5%.
- ``resilience_recovery_time_s`` — crash-to-trained: construct a fresh
  trainer, restore the newest checkpoint, run the first post-restore
  step (the full resume path a real crash pays).
- ``resilience_bitwise_ok`` — the recovery is *correct*, not just fast:
  a run crashed at the midpoint and resumed finishes with params
  byte-identical to the uncrashed run at the same step count.
- ``resilience_ckpt_bytes`` — snapshot size on disk.
- ``server_recovery_time_s`` — PS server crash-to-serving: construct a
  fresh ``PSServer`` over the crashed server's state dir (snapshot load
  + WAL replay, the full failover path a respawned server pays).
- ``wal_replay_rate_keys_per_s`` — WAL push records replayed per second
  during that recovery.
- ``server_snapshot_overhead_pct`` — push-apply loop with snapshot+WAL
  persistence armed vs unarmed; the acceptance gate is < 5 %.
- ``server_recovery_bitwise_ok`` — the recovered store is byte-identical
  to the crashed server's in-memory state.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

import numpy as np


def _fresh_trainer(seed):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DataParallelTrainer
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})


def _params_bytes(trainer):
    return b"".join(
        np.asarray(p.data()._data).tobytes()
        for _, p in sorted(trainer._params_by_name.items()))


def _server_stage():
    """PS server durability numbers, sockets elided: pushes are applied
    through ``PSServer._handle`` directly (the same apply/WAL/snapshot
    path the wire hits) so the measurement is the persistence cost, not
    TCP.  The 'crash' is ``stop()`` without a final snapshot — recovery
    must replay the WAL tail."""
    from mxnet_tpu import kvstore_ps

    import pickle

    from mxnet_tpu import optimizer as opt

    d = tempfile.mkdtemp(prefix="mxtpu_ps_state_bench_")
    keys = ["w%03d" % i for i in range(16)]
    size = 2048
    rng = np.random.RandomState(0)
    grads = [rng.rand(size).astype(np.float32) for _ in range(8)]
    pushes = int(os.environ.get("MXTPU_RES_BENCH_SERVER_PUSHES", "192"))
    # deliberately NOT a divisor of `pushes`: a WAL tail must be left
    # behind for the recovery below to actually replay
    cadence = 128
    opt_blob = pickle.dumps(opt.create("sgd", learning_rate=0.05,
                                       momentum=0.9))

    def make(state_dir, snapshot_every):
        # a real server-side updater (SGD+momentum), so the overhead
        # denominator is an honest apply cost, not a free memcpy
        srv = kvstore_ps.PSServer(port=0, state_dir=state_dir,
                                  snapshot_every=snapshot_every)
        ctx = {"staging": {}, "snapshots": {}, "claimed_inits": set(),
               "rank": 0}
        srv._handle(("set_optimizer", opt_blob), ctx)
        for k in keys:
            srv._handle(("init", k, np.zeros(size, np.float32)), ctx)
        return [srv, ctx, 0]

    def window(cfg):
        srv, ctx, step = cfg
        t0 = time.perf_counter()
        for i in range(pushes):
            step += 1
            srv._handle(("push", keys[i % len(keys)], "dense",
                         grads[i % len(grads)], step), ctx)
        cfg[2] = step
        return time.perf_counter() - t0

    d_wal = tempfile.mkdtemp(prefix="mxtpu_ps_state_bench_wal_")
    try:
        # three configs timed in INTERLEAVED min-of-3 windows (1-core CI
        # hosts drift): plain apply, +WAL, +WAL+snapshots.  The gated
        # number is the SNAPSHOT increment — per-push WAL cost is the
        # price of exactly-once replay and is reported separately.
        plain = make(None, None)
        wal_only = make(d_wal, None)
        armed = make(d, cadence)
        for cfg in (plain, wal_only, armed):
            window(cfg)                  # warm updater states + jit
        times = {id(plain): None, id(wal_only): None, id(armed): None}
        for _ in range(3):
            for cfg in (plain, wal_only, armed):
                dt = window(cfg)
                key = id(cfg)
                times[key] = dt if times[key] is None else min(times[key],
                                                               dt)
        dt_plain = times[id(plain)]
        wal_overhead = 100.0 * (times[id(wal_only)] - dt_plain) \
            / max(dt_plain, 1e-9)
        snap_overhead = 100.0 * (times[id(armed)] - times[id(wal_only)]) \
            / max(dt_plain, 1e-9)
        plain[0].stop()
        wal_only[0].stop()
        # guarantee a WAL tail past the newest snapshot (the windows may
        # have ended exactly on a cadence boundary) so the recovery
        # below really replays, then "crash" — no final snapshot
        srv, ctx, step = armed
        srv._join_snapshot_thread()
        srv._snapshot_every = None
        for i in range(64):
            step += 1
            srv._handle(("push", keys[i % len(keys)], "dense",
                         grads[i % len(grads)], step), ctx)
        blob = b"".join(srv._store[k].tobytes() for k in keys)
        srv.stop()

        t0 = time.perf_counter()
        recovered = kvstore_ps.PSServer(port=0, state_dir=d)
        recovery_s = time.perf_counter() - t0
        replayed = recovered.recovered_wal_records
        rate = replayed / max(recovered.recovery_replay_s, 1e-9)
        ok = b"".join(recovered._store[k].tobytes() for k in keys) == blob
        recovered.stop()
        return {
            "server_recovery_time_s": round(recovery_s, 3),
            "wal_replay_rate_keys_per_s": round(rate, 1),
            "server_snapshot_overhead_pct": round(snap_overhead, 2),
            "server_wal_overhead_pct": round(wal_overhead, 2),
            "server_wal_replayed": replayed,
            "server_recovery_bitwise_ok": bool(ok),
            "server_bench_pushes": pushes,
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)
        shutil.rmtree(d_wal, ignore_errors=True)


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.trainer import DEFAULT_CHECKPOINT_EVERY

    steps = int(os.environ.get("MXTPU_RES_BENCH_STEPS", "300"))
    cadence = DEFAULT_CHECKPOINT_EVERY
    batch = 32
    rng = np.random.RandomState(0)
    batches = [(mx.nd.array(rng.rand(batch, 20).astype(np.float32)),
                mx.nd.array(rng.randint(0, 10, batch).astype(np.int64)))
               for _ in range(8)]
    ckdir = tempfile.mkdtemp(prefix="mxtpu_res_bench_")
    try:
        # warm the step jit outside every timed window
        t = _fresh_trainer(0)
        for i in range(3):
            t.step(*batches[i % len(batches)])
        t.flush()

        # plain loop vs auto-checkpointing loop, identical step streams
        t1 = _fresh_trainer(1)
        t1.step(*batches[0])
        t1.flush()
        t0w = time.perf_counter()
        for i in range(steps):
            t1.step(*batches[i % len(batches)])
        t1.flush()
        dt_plain = time.perf_counter() - t0w

        t2 = _fresh_trainer(1)
        t2.step(*batches[0])
        t2.flush()
        t2.save_checkpoint(ckdir, epoch=0, nbatch=0)  # warm dir + pickling
        t0w = time.perf_counter()
        for i in range(steps):
            t2.step(*batches[i % len(batches)])
            if t2._step_count % cadence == 0:
                t2.save_checkpoint(ckdir, epoch=0, nbatch=i)
        t2.flush()
        dt_ckpt = time.perf_counter() - t0w
        # the cadence may not divide the loop; guarantee >= 1 snapshot so
        # recovery below always has something to restore
        last = t2.save_checkpoint(ckdir, epoch=0, nbatch=steps - 1)
        overhead_pct = 100.0 * (dt_ckpt - dt_plain) / max(dt_plain, 1e-9)

        # bitwise recovery proof: run A straight, run B crash+resume
        n_total, n_crash = 16, 8
        ta = _fresh_trainer(2)
        for i in range(n_total):
            ta.step(*batches[i % len(batches)])
        ta.flush()
        ref = _params_bytes(ta)

        tb = _fresh_trainer(2)
        for i in range(n_crash):
            tb.step(*batches[i % len(batches)])
        crash_dir = os.path.join(ckdir, "crash")
        tb.save_checkpoint(crash_dir, epoch=0, nbatch=n_crash - 1)
        del tb  # the "crash"

        t0w = time.perf_counter()
        tc = _fresh_trainer(3)   # wrong seed on purpose: restore must win
        tc.restore_checkpoint(crash_dir)
        tc.step(*batches[n_crash % len(batches)])
        tc.flush()
        recovery_s = time.perf_counter() - t0w
        for i in range(n_crash + 1, n_total):
            tc.step(*batches[i % len(batches)])
        tc.flush()
        bitwise_ok = _params_bytes(tc) == ref

        rec = {
            "resilience_checkpoint_overhead_pct": round(overhead_pct, 2),
            "resilience_recovery_time_s": round(recovery_s, 3),
            "resilience_bitwise_ok": bool(bitwise_ok),
            "resilience_ckpt_bytes": os.path.getsize(last),
            "resilience_ckpt_cadence": cadence,
            "resilience_bench_steps": steps,
        }
        rec.update(_server_stage())
        print(json.dumps(rec))
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)


if __name__ == "__main__":
    main()
