"""Host-only resilience micro-bench: ``python -m mxnet_tpu.resilience.bench``.

Run by ``bench.py``'s ``resilience`` stage as a ``JAX_PLATFORMS=cpu``
subprocess BEFORE backend acquisition (the r05 pattern), so the numbers
stay live when the TPU backend is down.  Prints ONE JSON line:

- ``resilience_checkpoint_overhead_pct`` — extra wall time of a training
  loop that auto-checkpoints at the default cadence
  (``DEFAULT_CHECKPOINT_EVERY``) vs the same loop without; the
  acceptance gate is < 5%.
- ``resilience_recovery_time_s`` — crash-to-trained: construct a fresh
  trainer, restore the newest checkpoint, run the first post-restore
  step (the full resume path a real crash pays).
- ``resilience_bitwise_ok`` — the recovery is *correct*, not just fast:
  a run crashed at the midpoint and resumed finishes with params
  byte-identical to the uncrashed run at the same step count.
- ``resilience_ckpt_bytes`` — snapshot size on disk.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

import numpy as np


def _fresh_trainer(seed):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DataParallelTrainer
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})


def _params_bytes(trainer):
    return b"".join(
        np.asarray(p.data()._data).tobytes()
        for _, p in sorted(trainer._params_by_name.items()))


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.trainer import DEFAULT_CHECKPOINT_EVERY

    steps = int(os.environ.get("MXTPU_RES_BENCH_STEPS", "300"))
    cadence = DEFAULT_CHECKPOINT_EVERY
    batch = 32
    rng = np.random.RandomState(0)
    batches = [(mx.nd.array(rng.rand(batch, 20).astype(np.float32)),
                mx.nd.array(rng.randint(0, 10, batch).astype(np.int64)))
               for _ in range(8)]
    ckdir = tempfile.mkdtemp(prefix="mxtpu_res_bench_")
    try:
        # warm the step jit outside every timed window
        t = _fresh_trainer(0)
        for i in range(3):
            t.step(*batches[i % len(batches)])
        t.flush()

        # plain loop vs auto-checkpointing loop, identical step streams
        t1 = _fresh_trainer(1)
        t1.step(*batches[0])
        t1.flush()
        t0w = time.perf_counter()
        for i in range(steps):
            t1.step(*batches[i % len(batches)])
        t1.flush()
        dt_plain = time.perf_counter() - t0w

        t2 = _fresh_trainer(1)
        t2.step(*batches[0])
        t2.flush()
        t2.save_checkpoint(ckdir, epoch=0, nbatch=0)  # warm dir + pickling
        t0w = time.perf_counter()
        for i in range(steps):
            t2.step(*batches[i % len(batches)])
            if t2._step_count % cadence == 0:
                t2.save_checkpoint(ckdir, epoch=0, nbatch=i)
        t2.flush()
        dt_ckpt = time.perf_counter() - t0w
        # the cadence may not divide the loop; guarantee >= 1 snapshot so
        # recovery below always has something to restore
        last = t2.save_checkpoint(ckdir, epoch=0, nbatch=steps - 1)
        overhead_pct = 100.0 * (dt_ckpt - dt_plain) / max(dt_plain, 1e-9)

        # bitwise recovery proof: run A straight, run B crash+resume
        n_total, n_crash = 16, 8
        ta = _fresh_trainer(2)
        for i in range(n_total):
            ta.step(*batches[i % len(batches)])
        ta.flush()
        ref = _params_bytes(ta)

        tb = _fresh_trainer(2)
        for i in range(n_crash):
            tb.step(*batches[i % len(batches)])
        crash_dir = os.path.join(ckdir, "crash")
        tb.save_checkpoint(crash_dir, epoch=0, nbatch=n_crash - 1)
        del tb  # the "crash"

        t0w = time.perf_counter()
        tc = _fresh_trainer(3)   # wrong seed on purpose: restore must win
        tc.restore_checkpoint(crash_dir)
        tc.step(*batches[n_crash % len(batches)])
        tc.flush()
        recovery_s = time.perf_counter() - t0w
        for i in range(n_crash + 1, n_total):
            tc.step(*batches[i % len(batches)])
        tc.flush()
        bitwise_ok = _params_bytes(tc) == ref

        print(json.dumps({
            "resilience_checkpoint_overhead_pct": round(overhead_pct, 2),
            "resilience_recovery_time_s": round(recovery_s, 3),
            "resilience_bitwise_ok": bool(bitwise_ok),
            "resilience_ckpt_bytes": os.path.getsize(last),
            "resilience_ckpt_cadence": cadence,
            "resilience_bench_steps": steps,
        }))
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)


if __name__ == "__main__":
    main()
