"""Shared retry/backoff policy: exponential backoff with jitter.

Every transient-failure site in the stack retries the same way — the
bench's backend acquisition (``bench.py _acquire_devices``), the
launcher's crashed-rank restarts (``tools/launch.py --restart-failed``)
and the kvstore client's push/pull RPC reconnects (``kvstore_ps.PSClient``)
all draw their delays from one :class:`BackoffPolicy` instead of three
divergent hand-rolled loops.  Jitter is the load-shedding half of the
policy (reference: ps-lite's van retry + the classic "exponential backoff
and jitter" result): N workers that all lost the same server must not
redial in lockstep.

Deliberately dependency-free (stdlib only): ``tools/launch.py`` loads this
file directly by path so the launcher never imports jax.
"""
from __future__ import annotations

import random
import time

__all__ = ["BackoffPolicy", "retry_call", "RetriesExhausted"]


class RetriesExhausted(RuntimeError):
    """``retry_call`` ran out of attempts; ``__cause__`` is the last error."""


class BackoffPolicy:
    """Exponential backoff with multiplicative jitter.

    delay(attempt) = min(base_s * factor**attempt, max_delay_s) * U,
    with U uniform in [1-jitter, 1+jitter] from a policy-local RNG —
    ``seed`` pins the jitter stream so a chaos test (or a BENCH record)
    replays the exact same schedule.

    Parameters
    ----------
    base_s : first delay, seconds.
    factor : multiplier per attempt.
    max_delay_s : cap on the un-jittered delay.
    max_retries : attempts ``retry_call``/``delays`` will make.
    jitter : half-width of the multiplicative jitter band (0 disables).
    seed : int or None — None uses nondeterministic jitter.
    """

    def __init__(self, base_s=0.5, factor=2.0, max_delay_s=30.0,
                 max_retries=8, jitter=0.25, seed=None):
        if base_s <= 0 or factor < 1.0:
            raise ValueError("need base_s > 0 and factor >= 1, got %r/%r"
                             % (base_s, factor))
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1), got %r" % (jitter,))
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.max_delay_s = float(max_delay_s)
        self.max_retries = int(max_retries)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay(self, attempt):
        """Jittered delay for 0-based ``attempt``."""
        d = min(self.base_s * self.factor ** int(attempt), self.max_delay_s)
        if self.jitter:
            d *= self._rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)
        return d

    def delays(self):
        """The full delay schedule: ``max_retries`` jittered delays."""
        return [self.delay(a) for a in range(self.max_retries)]

    def sleep(self, attempt):
        """Sleep the jittered delay for ``attempt``; returns it."""
        d = self.delay(attempt)
        time.sleep(d)
        return d


def retry_call(fn, *args, policy=None, retry_on=(OSError, ConnectionError),
               on_retry=None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying on ``retry_on`` with the
    policy's backoff.  ``on_retry(attempt, exc, delay)`` (if given) is
    called before each sleep — the hook error-history recorders (the
    bench's ``backend_error_history``) plug into.  Raises
    :class:`RetriesExhausted` from the last error once attempts run out.
    """
    policy = policy or BackoffPolicy()
    last = None
    for attempt in range(policy.max_retries + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            last = e
            if attempt >= policy.max_retries:
                break
            d = policy.delay(attempt)
            if on_retry is not None:
                on_retry(attempt, e, d)
            time.sleep(d)
    raise RetriesExhausted(
        "%s failed after %d attempts: %s"
        % (getattr(fn, "__name__", fn), policy.max_retries + 1,
           last)) from last
