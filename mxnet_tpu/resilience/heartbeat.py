"""Worker heartbeats + server-side watchdog for the elastic kvstore tier.

Reference: ps-lite's van-level heartbeats behind
``kvstore.h:339 get_num_dead_node`` — workers ping the scheduler, a
silence window marks them dead.  Here the pieces are factored so both
the PS server (``kvstore_ps.PSServer``) and tests can use them directly:

- :class:`HeartbeatMonitor` — server side.  ``beat(rank, step)`` records
  liveness and training progress; a watchdog thread (``start()``)
  declares ranks dead after ``timeout_s`` of silence and runs the
  ``on_dead`` callback (the PS uses it to close the rank's socket and
  reassign its keys).  ``max_step()`` is the staleness reference point
  for the bounded-staleness rejoin gate.
- :class:`HeartbeatSender` — worker side.  A daemon thread calling
  ``beat_fn`` every ``interval_s``; send errors are swallowed (a beat is
  best-effort — the *absence* of beats is the signal).

Both loops poll with bounded waits (``Event.wait(timeout)``) — the exact
discipline the SRC005 lint enforces on every worker loop in this repo.
"""
from __future__ import annotations

import logging
import threading
import time

__all__ = ["HeartbeatMonitor", "HeartbeatSender"]


class HeartbeatMonitor:
    """Track per-rank last-beat times; declare silence as death."""

    def __init__(self, timeout_s=10.0, poll_s=None, on_dead=None):
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s else max(0.05,
                                                       self.timeout_s / 4.0)
        self._on_dead = on_dead
        self._lock = threading.Lock()
        self._last = {}      # rank -> monotonic last-beat time
        self._steps = {}     # rank -> last reported step
        self._dead = set()
        self._stop = threading.Event()
        self._thread = None

    # -- recording ---------------------------------------------------------
    def beat(self, rank, step=None):
        """Record a heartbeat; a beat from a dead rank is a rejoin."""
        with self._lock:
            self._last[rank] = time.monotonic()
            self._dead.discard(rank)
            if step is not None:
                self._steps[rank] = max(int(step),
                                        self._steps.get(rank, 0))

    def note_step(self, rank, step):
        """Progress without a liveness claim (e.g. learned from a push)."""
        with self._lock:
            if step is not None:
                self._steps[rank] = max(int(step),
                                        self._steps.get(rank, 0))

    # -- queries -----------------------------------------------------------
    def max_step(self):
        with self._lock:
            return max(self._steps.values()) if self._steps else 0

    def step_of(self, rank):
        with self._lock:
            return self._steps.get(rank, 0)

    def steps(self):
        """Copy of the per-rank step clocks (the PS snapshots this so a
        recovered server's staleness gate keeps its reference points)."""
        with self._lock:
            return dict(self._steps)

    def lag_s(self, now=None):
        """Seconds since each rank's last beat — the telemetry gauge
        (``mxtpu_ps_heartbeat_lag_seconds``) behind the watchdog's
        verdicts: lag approaching ``timeout_s`` is the early warning."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return {rank: now - last for rank, last in self._last.items()}

    def dead(self):
        with self._lock:
            return set(self._dead)

    def live(self):
        with self._lock:
            return {r for r in self._last if r not in self._dead}

    # -- the watchdog ------------------------------------------------------
    def check(self, now=None):
        """One watchdog scan; returns the ranks newly declared dead.
        ``on_dead`` runs outside the lock (it may call back in)."""
        now = time.monotonic() if now is None else now
        newly = []
        with self._lock:
            for rank, last in self._last.items():
                if rank not in self._dead and now - last > self.timeout_s:
                    self._dead.add(rank)
                    newly.append(rank)
        for rank in newly:
            if self._on_dead is not None:
                self._on_dead(rank)
        return newly

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._watch,
                                            name="mxtpu-hb-watchdog",
                                            daemon=True)
            self._thread.start()
        return self

    def _watch(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:
                # an on_dead callback error must not kill the watchdog:
                # with this thread gone, dead-rank detection (and key
                # reassignment) silently stops for the rest of the run
                logging.getLogger(__name__).exception(
                    "heartbeat watchdog scan failed; continuing")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class HeartbeatSender:
    """Worker-side beat loop: call ``beat_fn()`` every ``interval_s``."""

    def __init__(self, beat_fn, interval_s=2.0):
        self._fn = beat_fn
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="mxtpu-hb-sender", daemon=True)
        self.beats = 0

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self._fn()
                self.beats += 1
            except Exception:
                # best-effort: a failed beat just widens the silence the
                # watchdog measures; the sender must not die of it
                pass

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)
