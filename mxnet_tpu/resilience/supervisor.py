"""Elastic training supervisor: rank death → shrink, rejoin → grow.

The TensorFlow-paper ecosystem (arxiv 1605.08695 §4.2) treats
checkpoints as the unit of fault tolerance and fleet size as a variable;
this module is that policy for the ZeRO-1 elastic tier
(docs/elastic.md): it launches the SPMD training job
(``tools/train_elastic.py``) over a rank set, watches the per-rank
heartbeat records the job writes, and when the job dies it names the
dead rank, **shrinks** the rank set, and relaunches with ``--resume`` —
the shard-parallel checkpoint re-shards to the new size on load.  A
rank announcing itself (a join record) triggers a **grow** the same
way: the running job is asked to yield (SIGTERM → checkpoint + clean
exit), then relaunched one rank larger.

Decision discipline (the PR-12 promotion-controller contract, SRV005):
:func:`ElasticSupervisor.decide` is a *pure* function of an observation
dict — no clock ever feeds a decision.  Liveness is process exit (the
real signal when one host of an SPMD job dies, the whole job dies),
victim naming comes from the heartbeat step counters (the unique rank
that *entered* the last started step but never completed its probe),
and ``steps_lost`` is heartbeat-trained-step minus manifest step.
Every committed decision is:

- a versioned JSON audit record (``audit-<seq>.json``, atomic rename,
  ``schema_version`` 1, readers refuse newer) carrying the decision AND
  the observation it was made from;
- a chaos probe hit (site ``supervisor.decision``, count = seq) so
  schedules can fault the supervisor itself;
- a telemetry flight-ring event + ``mxtpu_supervisor_decisions_total``
  counter when telemetry is armed.

Heartbeat/join records are plain JSON files in the work directory
(atomic rename), written by the training job — see
:func:`write_heartbeat` / :func:`write_join_request`.

jax is imported nowhere here: the supervisor must run on a host whose
backend is wedged (that is rather the point).
"""
from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import time

from . import chaos as _chaos
from . import checkpoint as _ckpt

__all__ = ["AUDIT_SCHEMA_VERSION", "ElasticSupervisor", "write_heartbeat",
           "read_heartbeats", "write_join_request", "read_join_requests",
           "read_audit", "SupervisorHalted"]

AUDIT_SCHEMA_VERSION = 1

# exit code a worker uses for "yielded cleanly for a fleet change"
# (SIGTERM handled: checkpoint written, not a crash, not completion)
YIELD_EXIT_CODE = 3


class SupervisorHalted(RuntimeError):
    """The supervisor gave up (below min fleet size, or the restart
    budget for deaths it could not attribute is exhausted)."""


def _atomic_json(path, doc):
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# heartbeat / join records (written by the training job)
# ---------------------------------------------------------------------------
def write_heartbeat(directory, rank, enter_step, done_step, trained_step):
    """Atomically publish rank ``rank``'s liveness record.  ``enter``/
    ``done`` bracket the rank's per-step probe (``train.step``):
    a rank that entered step *s* but never completed it is the
    supervisor's victim candidate; ``trained_step`` is the last step
    whose update actually committed (what ``steps_lost`` measures
    against the manifest)."""
    _atomic_json(os.path.join(directory, "hb-%05d.json" % int(rank)),
                 {"rank": int(rank), "enter_step": int(enter_step),
                  "done_step": int(done_step),
                  "trained_step": int(trained_step),
                  "pid": os.getpid()})


def read_heartbeats(directory):
    """{rank: record} of every parseable heartbeat file."""
    out = {}
    for path in sorted(glob.glob(os.path.join(directory, "hb-*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
            out[int(rec["rank"])] = rec
        except (OSError, ValueError, KeyError):
            continue
    return out


def clear_heartbeats(directory):
    for path in glob.glob(os.path.join(directory, "hb-*.json")):
        try:
            os.remove(path)
        except OSError:
            pass


def write_join_request(directory, rank):
    """A (re)joining rank announces itself; the supervisor grows the
    fleet at the next safe point (job yield)."""
    _atomic_json(os.path.join(directory, "join-%05d.json" % int(rank)),
                 {"rank": int(rank), "pid": os.getpid()})


def read_join_requests(directory):
    out = []
    for path in sorted(glob.glob(os.path.join(directory, "join-*.json"))):
        try:
            with open(path) as f:
                out.append(int(json.load(f)["rank"]))
        except (OSError, ValueError, KeyError):
            continue
    return sorted(set(out))


def clear_join_requests(directory):
    for path in glob.glob(os.path.join(directory, "join-*.json")):
        try:
            os.remove(path)
        except OSError:
            pass


def read_audit(directory):
    """The committed decision trail, ascending by seq.  Refuses records
    from a NEWER schema (the PR-12 versioned-reader discipline)."""
    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "audit-*.json"))):
        with open(path) as f:
            doc = json.load(f)
        ver = int(doc.get("schema_version", 0))
        if ver > AUDIT_SCHEMA_VERSION:
            raise ValueError(
                "audit record %s has schema_version %d; this reader "
                "understands <= %d — upgrade the reader, do not guess "
                "at decision records" % (os.path.basename(path), ver,
                                         AUDIT_SCHEMA_VERSION))
        out.append(doc)
    out.sort(key=lambda d: d.get("seq", 0))
    return out


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------
class ElasticSupervisor:
    """Launch/watch/heal one elastic training job (module docstring).

    Parameters
    ----------
    workdir : str — heartbeats, join records, the audit trail and (by
        convention) the job's checkpoint directory live here.
    launch : callable(ranks, resume, extra_env) -> subprocess.Popen —
        starts the training job over ``ranks``.  ``extra_env`` carries
        the first-launch-only chaos spec (a respawned job must not
        re-arm the fault that killed its predecessor).
    ranks : initial rank ids (fleet size = len(ranks)).
    min_size : refuse to shrink below this many ranks.
    max_restarts : budget for deaths with NO attributable dead rank
        (a crash-looping job must not restart forever).
    target_steps : the job's step goal — recorded in audit evidence.
    chaos_env : optional {var: value} merged into the FIRST launch only.
    poll_interval_s : how often the watch loop samples the child and
        the join records (measurement, never a decision input).
    """

    def __init__(self, workdir, launch, ranks, min_size=1,
                 max_restarts=2, target_steps=None, chaos_env=None,
                 poll_interval_s=0.1, logger=None):
        import logging
        self.workdir = str(workdir)
        self.audit_dir = os.path.join(self.workdir, "audit")
        os.makedirs(self.audit_dir, exist_ok=True)
        self._launch = launch
        self.ranks = sorted(int(r) for r in ranks)
        self.min_size = int(min_size)
        self.max_restarts = int(max_restarts)
        self.target_steps = target_steps
        self._chaos_env = dict(chaos_env or {})
        self._poll_s = float(poll_interval_s)
        self._seq = 0
        self._restarts_used = 0
        self._launches = 0
        self.log = logger or logging.getLogger("mxtpu.supervisor")

    # -- observation -------------------------------------------------------
    def observe(self, exit_code):
        """Snapshot the facts a decision may depend on: the job's exit
        code, the per-rank heartbeat counters, the newest committed
        manifest step and any pending join requests.  Pure reads — the
        returned dict IS the decision input and is embedded verbatim in
        the audit record."""
        found = _ckpt.latest_sharded_checkpoint(self.workdir)
        manifest_step = int(found[1]["step"]) if found else 0
        return {
            "exit_code": exit_code,
            "ranks": list(self.ranks),
            "heartbeats": {str(r): rec for r, rec in
                           sorted(read_heartbeats(self.workdir).items())},
            "manifest_step": manifest_step,
            "join_requests": read_join_requests(self.workdir),
            "target_steps": self.target_steps,
            "restarts_used": self._restarts_used,
        }

    # -- the pure decision rule -------------------------------------------
    @staticmethod
    def decide(obs, min_size=1, max_restarts=2):
        """Pure decision function: observation dict -> decision dict
        (``action`` ∈ start/complete/grow/shrink/restart/halt, plus
        ``ranks``/``dead_rank``/``steps_lost``/``reason``).  No clock,
        no randomness, no IO — byte-identical reruns make byte-identical
        decisions (the SRV005 contract; tests replay it).

        Victim rule: among the current ranks, the unique rank whose
        heartbeat *entered* the most recent step but never completed its
        probe (``done_step < enter_step``) with the HIGHEST
        ``enter_step`` is the dead rank — per-rank probes run in rank
        order, so the first rank that fails to complete the step the
        fleet was starting is the one whose host died; later ranks
        never reached it."""
        ranks = list(obs["ranks"])
        hbs = {int(r): rec for r, rec in obs["heartbeats"].items()
               if int(r) in ranks}
        trained = max([rec.get("trained_step", 0)
                       for rec in hbs.values()] or [0])
        steps_lost = max(0, trained - int(obs["manifest_step"]))
        joins = [r for r in obs.get("join_requests", ())
                 if r not in ranks]
        exit_code = obs["exit_code"]

        if exit_code == 0:
            return {"action": "complete", "ranks": ranks,
                    "dead_rank": None, "steps_lost": 0,
                    "reason": "job finished its step budget"}
        if exit_code == YIELD_EXIT_CODE:
            new_ranks = sorted(ranks + joins)
            return {"action": "grow" if joins else "restart",
                    "ranks": new_ranks, "dead_rank": None,
                    "steps_lost": steps_lost,
                    "reason": "job yielded for a fleet change"}

        # crashed: name the victim from the heartbeat counters
        candidates = [
            (rec.get("enter_step", 0), r) for r, rec in hbs.items()
            if rec.get("done_step", 0) < rec.get("enter_step", 0)]
        dead = max(candidates)[1] if candidates else None
        if dead is not None:
            survivors = [r for r in ranks if r != dead]
            if len(survivors) >= min_size:
                return {"action": "shrink", "ranks": survivors,
                        "dead_rank": dead, "steps_lost": steps_lost,
                        "reason": "rank %d entered step %d and never "
                                  "completed its probe (exit %s); "
                                  "resuming at size %d from manifest "
                                  "step %d"
                                  % (dead, max(candidates)[0],
                                     exit_code, len(survivors),
                                     obs["manifest_step"])}
            return {"action": "halt", "ranks": ranks, "dead_rank": dead,
                    "steps_lost": steps_lost,
                    "reason": "rank %d died but shrinking below "
                              "min_size=%d is refused" % (dead,
                                                          min_size)}
        if int(obs.get("restarts_used", 0)) < max_restarts:
            return {"action": "restart", "ranks": ranks,
                    "dead_rank": None, "steps_lost": steps_lost,
                    "reason": "job died (exit %s) with no attributable "
                              "dead rank; restart %d/%d"
                              % (exit_code,
                                 int(obs.get("restarts_used", 0)) + 1,
                                 max_restarts)}
        return {"action": "halt", "ranks": ranks, "dead_rank": None,
                "steps_lost": steps_lost,
                "reason": "restart budget exhausted (exit %s)"
                          % (exit_code,)}

    # -- decision commit: chaos probe + audit + telemetry ------------------
    def _commit(self, decision, obs):
        self._seq += 1
        seq = self._seq
        # chaos first: an injected fault here models a supervisor that
        # dies BEFORE committing — no audit record may be written for
        # an uncommitted decision
        _chaos.maybe_inject("supervisor.decision", seq, ctx=decision)
        record = {"schema_version": AUDIT_SCHEMA_VERSION, "seq": seq,
                  "decision": dict(decision), "evidence": dict(obs)}
        _atomic_json(os.path.join(self.audit_dir,
                                  "audit-%06d.json" % seq), record)
        try:
            from .. import telemetry as _tele
            if _tele.enabled():
                _tele.record("supervisor.decision", seq=seq,
                             action=decision["action"],
                             dead_rank=decision.get("dead_rank"),
                             size=len(decision.get("ranks", ())),
                             steps_lost=decision.get("steps_lost"))
            from ..telemetry.metrics import registry as _registry
            _registry().counter(
                "mxtpu_supervisor_decisions_total",
                "elastic supervisor decisions by action").inc(
                action=decision["action"])
        except Exception:
            pass  # telemetry must never block or reorder a decision
        self.log.info("supervisor decision #%d: %s (%s)", seq,
                      decision["action"], decision["reason"])
        return decision

    # -- the watch loop ----------------------------------------------------
    def _spawn(self, ranks, resume):
        extra = dict(self._chaos_env) if self._launches == 0 else {}
        self._launches += 1
        clear_heartbeats(self.workdir)
        return self._launch(list(ranks), resume, extra)

    def _wait(self, proc):
        """Block until the job exits; a NEW join request asks the job to
        yield (SIGTERM) so the fleet can grow.  This loop is
        measurement/IO pacing only — nothing it reads from the clock
        feeds a decision."""
        asked_to_yield = False
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if not asked_to_yield and read_join_requests(self.workdir):
                if any(r not in self.ranks for r in
                       read_join_requests(self.workdir)):
                    asked_to_yield = True
                    try:
                        proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
            time.sleep(self._poll_s)  # mxlint: disable=SRV005 — child-process poll pacing, not a decision input

    def run(self):
        """Supervise to completion.  Returns the final decision dict
        (action ``complete``); raises :class:`SupervisorHalted` when
        healing is impossible."""
        obs = self.observe(exit_code=None)
        decision = self._commit(
            {"action": "start", "ranks": list(self.ranks),
             "dead_rank": None, "steps_lost": 0,
             "reason": "initial launch at size %d" % len(self.ranks)},
            obs)
        resume = _ckpt.latest_sharded_checkpoint(self.workdir) is not None
        while True:
            proc = self._spawn(decision["ranks"], resume)
            rc = self._wait(proc)
            obs = self.observe(exit_code=rc)
            decision = self._commit(
                self.decide(obs, min_size=self.min_size,
                            max_restarts=self.max_restarts), obs)
            action = decision["action"]
            if action == "complete":
                return decision
            if action == "halt":
                raise SupervisorHalted(decision["reason"])
            if action == "restart":
                self._restarts_used += 1
            if action in ("grow", "shrink"):
                self.ranks = list(decision["ranks"])
                clear_join_requests(self.workdir)
            resume = True
