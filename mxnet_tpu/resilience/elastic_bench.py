"""Host-only elastic-tier micro-bench:
``python -m mxnet_tpu.resilience.elastic_bench``.

Run by ``bench.py``'s ``elastic`` stage as a ``JAX_PLATFORMS=cpu``
subprocess BEFORE backend acquisition (the r05 pattern), so the numbers
stay live when the TPU backend is down.  Prints ONE JSON line:

- ``zero1_modeled_hbm_drop_pct`` — the ZeRO-1 memory win from the
  *runtime* tape (``DataParallelTrainer(zero=1).zero_report`` at the
  pinned ``ZERO1_GEOMETRY``, declared 8-way axis) vs the same trainer's
  replicated twin — the runtime counterpart of the ``static_cost``
  stage's fixture-derived ``modeled_zero1_hbm_drop_pct``.  Gated by
  ``tools/bench_compare.py`` (higher, 2%: deterministic model).
- ``reshard_restore_ms`` — wall time of the resize-on-resume path: a
  shard-parallel checkpoint saved by a 4-way fleet restored into a
  2-way trainer (manifest verify + shard reassembly + re-shard +
  device placement).  Gated lower with absolute slack (1-core host).
- ``elastic_resize_bitwise_ok`` — that restore reproduced the full
  optimizer state byte-exactly.
- ``supervisor_failover_steps_lost`` — a REAL failover: the elastic
  supervisor runs ``tools/train_elastic.py`` with a chaos SIGKILL of 1
  of 2 ranks mid-run, auto-shrinks and resumes; the number is the
  shrink decision's audited ``steps_lost`` (0 at checkpoint-every-step
  cadence).  Gated lower_abs with zero slack — losing steps at this
  cadence is a policy regression.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time


def _zero1_trainer(k_devices, zero=1):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.analysis.shard_fixtures import ZERO1_GEOMETRY as g
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh
    net = gluon.nn.HybridSequential()
    for h in g["hidden"]:
        net.add(gluon.nn.Dense(h, activation="relu"))
    net.add(gluon.nn.Dense(g["classes"]))
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((k_devices,), ("data",),
                     jax.devices()[:k_devices])
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": g["lr"], "momentum": g["momentum"]},
        mesh=mesh, zero=zero)


def _modeled_drop_pct():
    """The runtime-tape ZeRO-1 HBM story at the pinned geometry."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.analysis.cost import analyze_fn
    from mxnet_tpu.analysis.shard_fixtures import ZERO1_GEOMETRY as g
    from mxnet_tpu.ndarray import NDArray

    k = 8
    data_shape = (g["batch"] * k, g["in_dim"])
    label_shape = (g["batch"] * k,)
    tz = _zero1_trainer(1, zero=1)
    rep, findings, _ = tz.zero_report(
        data_shape=data_shape, label_shape=label_shape,
        label_dtype="int32", declared_axis_size=k)
    errors = [f for f in findings]
    tw = _zero1_trainer(1, zero=0)
    tw._setup(NDArray(jnp.zeros(data_shape, np.float32)),
              NDArray(jnp.zeros(label_shape, np.int32)))
    train_vals = tuple(tw._params_by_name[n].data()._data
                       for n in tw._train_names)
    aux_vals = tuple(tw._params_by_name[n].data()._data
                     for n in tw._aux_names)
    states = tuple(tw._states_raw)
    xs = jax.ShapeDtypeStruct((g["batch"], g["in_dim"]), np.float32)
    ys = jax.ShapeDtypeStruct((g["batch"],), np.int32)
    key = jax.ShapeDtypeStruct((2,), np.uint32)
    twin = analyze_fn(
        tw._build_replica_step(), train_vals, states, aux_vals, xs, ys,
        key, jnp.float32(0.01), jnp.int32(1),
        axis_env=[("data", k)], donate_argnums=(0, 1),
        host_argnums=(3, 4))
    drop = twin.peak_hbm_bytes - rep.peak_hbm_bytes
    return {
        "zero1_modeled_hbm_drop_pct": round(
            100.0 * drop / twin.peak_hbm_bytes, 2),
        "zero1_runtime_peak_hbm_bytes": int(rep.peak_hbm_bytes),
        "zero1_twin_peak_hbm_bytes": int(twin.peak_hbm_bytes),
        "zero1_runtime_findings": len(errors),
    }


def _reshard_stage():
    """Save at fleet size 4, restore (re-shard) at size 2, timed."""
    import numpy as np

    import mxnet_tpu as mx

    d = tempfile.mkdtemp(prefix="mxtpu_elastic_bench_")
    try:
        t4 = _zero1_trainer(4)
        rng = np.random.RandomState(0)
        for _ in range(3):
            t4.step(mx.nd.array(rng.rand(64, 16).astype(np.float32)),
                    mx.nd.array(rng.randint(0, 10, 64)
                                .astype(np.int64)))
        t4.flush()
        t4.save_checkpoint(d, epoch=0, nbatch=2)
        plan = t4._zero_plan
        ref = [np.asarray(v)[:plan.total].copy()
               for v in t4._zero_leaves()]
        t2 = _zero1_trainer(2)
        t0 = time.perf_counter()
        t2.restore_checkpoint(d)
        restore_ms = 1e3 * (time.perf_counter() - t0)
        got = [np.asarray(v)[:t2._zero_plan.total]
               for v in t2._zero_leaves()]
        ok = all(a.tobytes() == b.tobytes() for a, b in zip(ref, got))
        return {"reshard_restore_ms": round(restore_ms, 2),
                "elastic_resize_bitwise_ok": bool(ok)}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _failover_stage():
    """Real supervisor failover through tools/train_elastic.py: SIGKILL
    1 of 2 ranks at step 3, shrink + resume, report the audited
    steps_lost.  Skipped (None) outside a repo checkout."""
    from mxnet_tpu.resilience.supervisor import read_audit

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    driver = os.path.join(repo, "tools", "train_elastic.py")
    if not os.path.isfile(driver):
        return {}
    d = tempfile.mkdtemp(prefix="mxtpu_elastic_failover_")
    try:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("MXTPU_CHAOS", None)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        # rank 1 (position 1 of 2) dies at step 3: at = (3-1)*2 + 1 + 1
        out = subprocess.run(
            [sys.executable, driver, "--supervise", "--workdir", d,
             "--ranks", "0,1", "--steps", "6", "--batch", "16",
             "--checkpoint-every", "1", "--chaos", "train.step:6:kill"],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=repo)
        if out.returncode != 0:
            raise RuntimeError("failover run rc=%d: %s" % (
                out.returncode, (out.stderr or out.stdout)[-300:]))
        shrink = [rec for rec in read_audit(os.path.join(d, "audit"))
                  if rec["decision"]["action"] == "shrink"]
        if not shrink:
            raise RuntimeError("no shrink decision in the audit trail")
        dec = shrink[0]["decision"]
        return {
            "supervisor_failover_steps_lost": int(dec["steps_lost"]),
            "supervisor_failover_dead_rank": dec["dead_rank"],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main():
    # the reshard stage needs a 4-way virtual mesh; pin it BEFORE any
    # jax import (all jax imports here are function-local)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rec = {}
    rec.update(_modeled_drop_pct())
    rec.update(_reshard_stage())
    rec.update(_failover_stage())
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
