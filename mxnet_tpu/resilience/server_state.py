"""Durable parameter-server state: snapshots + a write-ahead log.

The PS server (``kvstore_ps.PSServer``) is the one process whose memory
holds state no worker can reconstruct — server-held weights and the
server-side optimizer (updater) state.  PR 6 made *worker* death a
non-event; this module closes the remaining crash domain the same way
ps-lite's server replication and TensorFlow's checkpointed-PS story
(arxiv 1605.08695 §4.2) do: the server's state survives the server.

Two cooperating pieces, both host-only (no jax import — usable from the
bench's CPU subprocess and from tooling):

- **snapshots** reuse the ``.mxckpt`` write-fsync-rename discipline from
  :mod:`.checkpoint` verbatim (``save_checkpoint``/``latest_checkpoint``
  with ``keep=`` pruning incl. crashed-save tmp debris) — a SIGKILL
  mid-snapshot can only leave a stray tmp file, never a torn snapshot.
- **WAL**: between snapshots, every applied mutation (init /
  set_optimizer / push / client incarnation change) is appended to
  ``wal-<seq>.mxwal`` as a CRC-framed pickled record.  Appends are
  ``flush()``ed per record: a SIGKILLed server loses at most the record
  it was mid-``write()`` on (the torn tail is detected by length/CRC and
  dropped at replay), and that push was never acked — the client
  re-sends it.  Power loss is out of scope, exactly as for checkpoints.

Recovery = newest loadable snapshot + replay of every WAL record with a
sequence number past the snapshot's.  Replay is idempotent: push records
carry ``(rank, push_step)`` and the server skips any pair at or below
the rank's recovered high-water mark, so a record replayed twice — or a
client re-sending the push the crash left in flight — applies exactly
once.

A monotonic **generation** counter (its own rename-atomic file, bumped
at every recovery-armed server start) rides the hello handshake so
clients can tell a server *failover* from a mere TCP blip and restart
per-connection state (staged chunked transfers) wholesale.
"""
from __future__ import annotations

import os
import pickle
import re
import struct
import threading
import zlib

from . import checkpoint as _ckpt

__all__ = ["ServerStateStore", "WAL_SUFFIX"]

WAL_SUFFIX = ".mxwal"
_WAL_RE = re.compile(r"^wal-(\d+)" + re.escape(WAL_SUFFIX) + r"$")
_FRAME = struct.Struct("<II")          # (body length, crc32(body))


def _wal_path(directory, base_seq):
    return os.path.join(directory, "wal-%012d%s" % (int(base_seq),
                                                    WAL_SUFFIX))


def _read_wal(path):
    """Yield ``(seq, record)`` entries; a torn tail (crash mid-append)
    ends iteration silently — everything before it is intact by CRC."""
    try:
        f = open(path, "rb")
    except OSError:
        return
    with f:
        while True:
            hdr = f.read(_FRAME.size)
            if len(hdr) < _FRAME.size:
                return
            n, crc = _FRAME.unpack(hdr)
            body = f.read(n)
            if len(body) < n or zlib.crc32(body) != crc:
                return
            try:
                seq, record = pickle.loads(body)
            except Exception:
                return
            yield int(seq), record


class ServerStateStore:
    """Snapshot + WAL persistence for one PS server's state directory.

    The caller (``PSServer``) serializes all mutations behind its own
    state lock, so appends never race; the internal lock only guards the
    file handle across the snapshot rotation."""

    def __init__(self, directory, keep=3):
        self.directory = str(directory)
        self.keep = int(keep)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._wal = None
        self._wal_base = 0

    # -- generation --------------------------------------------------------
    def bump_generation(self):
        """Read-increment-rename the generation file; returns the new
        generation (1 on a fresh directory).  Rename-atomic like the
        snapshots: two crashes between snapshots still bump twice."""
        path = os.path.join(self.directory, "GENERATION")
        gen = 0
        try:
            with open(path) as f:
                gen = int(f.read().strip())
        except (OSError, ValueError):
            pass
        gen += 1
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            f.write(str(gen))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return gen

    # -- WAL ---------------------------------------------------------------
    def wal_append(self, seq, record):
        """Append one ``(seq, record)`` frame and flush it to the OS.
        Survives SIGKILL (page cache outlives the process); per-record
        fsync would cost ~a disk flush per push for a durability class
        (power loss) the checkpoint tier does not claim either."""
        body = pickle.dumps((int(seq), record),
                            protocol=pickle.HIGHEST_PROTOCOL)
        frame = _FRAME.pack(len(body), zlib.crc32(body)) + body
        with self._lock:
            if self._wal is None:
                self._wal = open(_wal_path(self.directory, self._wal_base),
                                 "ab")
            self._wal.write(frame)
            self._wal.flush()

    def _wal_files(self):
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = _WAL_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    # -- snapshots ---------------------------------------------------------
    def save_snapshot(self, payload, seq):
        """Atomically install the snapshot covering WAL seqs <= ``seq``
        and rotate the WAL.  Old snapshots are pruned to ``keep``
        (checkpoint.py's discipline, tmp debris included).  WAL segments
        are pruned only when their NEWEST record is at or below the
        oldest retained snapshot's seq — a segment's base alone is not
        enough, because records appended between an async snapshot
        capture and this rotation land in the old segment with seqs
        PAST the snapshot.  Any retained snapshot keeps a complete
        replay chain behind it."""
        path = _ckpt.save_checkpoint(self.directory, payload, step=seq,
                                     keep=self.keep)
        retained = _ckpt.list_checkpoints(self.directory)
        floor = retained[0][0] if retained else int(seq)
        with self._lock:
            if self._wal is not None:
                self._wal.close()
            self._wal_base = int(seq)
            self._wal = open(_wal_path(self.directory, self._wal_base), "ab")
            for base, wpath in self._wal_files():
                if base == self._wal_base:
                    continue
                max_seq = base
                for rec_seq, _ in _read_wal(wpath):
                    max_seq = max(max_seq, rec_seq)
                if max_seq <= floor:
                    try:
                        os.remove(wpath)
                    except OSError:
                        pass
        return path

    # -- recovery ----------------------------------------------------------
    def recover(self):
        """-> ``(snapshot_payload_or_None, [(seq, record), ...])`` with the
        records strictly after the snapshot's seq, in order.  Subsequent
        appends continue into the newest snapshot's WAL segment."""
        snap = _ckpt.latest_checkpoint(self.directory)
        payload, base_seq = None, 0
        if snap is not None:
            payload = snap[1]["payload"]
            base_seq = int(snap[1]["step"])
        records = []
        for _, path in self._wal_files():
            for seq, record in _read_wal(path):
                if seq > base_seq:
                    records.append((seq, record))
        records.sort(key=lambda sr: sr[0])
        with self._lock:
            self._wal_base = base_seq
        return payload, records

    def close(self):
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
