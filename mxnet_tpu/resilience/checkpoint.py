"""Atomic write-rename training snapshots.

Reference: the stack's checkpoint story (``python/mxnet/model.py``
``save_checkpoint``/``load_checkpoint`` epoch files) plus TensorFlow's
treatment of checkpoint-based recovery as a first-class system property
(arxiv 1605.08695 §4.2).  Two system guarantees the reference files do
NOT give and this module does:

- **atomicity**: a snapshot is written to ``<name>.tmp.<pid>`` and
  ``os.replace``d into place — a crash (even SIGKILL) mid-save can only
  leave a stray tmp file, never a torn checkpoint; the previous snapshot
  stays loadable.  ``tests/test_resilience.py`` kills a saver mid-write
  (chaos site ``checkpoint.save``) and asserts exactly this.
- **self-describing completeness**: the payload carries params, optimizer
  state, RNG state AND the iterator cursor (epoch/batch), so ``resume=``
  replays to *bitwise-identical* post-crash convergence — not merely
  "params restored".
- **provenance** (the train→serve handoff, ISSUE 12): every snapshot
  embeds a content digest (sha256 over the encoded payload bytes — the
  exact bytes a restore would decode) plus the training coordinates
  ``(epoch, step, train_run_id)`` the caller supplies.  The serving
  fleet surfaces this through ``/stats`` and the promotion controller
  writes it into every audit record, so "which checkpoint is live?" has
  a byte-exact answer.  Same-content snapshots digest identically
  (deterministic pickling of a deterministically-built payload), which
  is what lets the mlops headline test prove byte-identical promotion
  decisions across full retrain+repromote reruns.

Format (version 1): one pickled dict — ``{"version", "step", "payload"}``
where arrays are encoded as ``("nd", dtype_str, shape, raw_bytes)``
tuples (``encode_array``), which round-trips bf16 and every other jax
dtype exactly (numpy's npz cannot).  jax is imported nowhere here: the
module stays host-only (usable by the bench's CPU subprocess and by
tooling that inspects checkpoints without a backend).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import re

import numpy as _np

from . import chaos as _chaos

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint",
           "list_checkpoints", "encode_array", "decode_array",
           "payload_digest", "provenance", "CKPT_SUFFIX", "FORMAT_VERSION",
           "ShardIntegrityError", "save_sharded_checkpoint",
           "load_sharded_checkpoint", "latest_sharded_checkpoint",
           "list_manifests", "SHARD_SUFFIX", "MANIFEST_SUFFIX",
           "SHARD_FORMAT_VERSION"]

CKPT_SUFFIX = ".mxckpt"
FORMAT_VERSION = 1
_NAME_RE = re.compile(r"^ckpt-(\d+)" + re.escape(CKPT_SUFFIX) + r"$")

# shard-parallel snapshots (ZeRO-1 elastic training, docs/elastic.md):
# one <step>.shard-<r>-of-<K> file per rank plus a last-committed
# manifest — the manifest is the COMMIT POINT (written last), so a rank
# SIGKILLed mid shard write leaves the previous complete checkpoint
# authoritative
SHARD_SUFFIX = ".mxshard"
MANIFEST_SUFFIX = ".mxmanifest"
SHARD_FORMAT_VERSION = 1
_MANIFEST_RE = re.compile(r"^ckpt-(\d+)" + re.escape(MANIFEST_SUFFIX)
                          + r"$")


class ShardIntegrityError(RuntimeError):
    """A manifest references a shard that is missing or whose bytes do
    not match its recorded digest — the checkpoint is NOT loadable and
    the error names the shard and the reason (provenance for what used
    to surface as an anonymous load-time exception)."""


def encode_array(x):
    """Array -> ``("nd", dtype, shape, bytes)`` — exact for every dtype
    numpy can name (bf16 included, via jax's ml_dtypes registration)."""
    a = _np.asarray(x)
    return ("nd", str(a.dtype), tuple(a.shape), a.tobytes())


def decode_array(enc):
    tag, dtype, shape, raw = enc
    assert tag == "nd", enc
    return _np.frombuffer(raw, dtype=_np.dtype(dtype)).reshape(shape)


def _ckpt_path(directory, step):
    return os.path.join(directory, "ckpt-%012d%s" % (int(step), CKPT_SUFFIX))


def payload_digest(payload):
    """sha256 hex digest of the pickled payload — the byte-exact identity
    of a checkpoint's content.  Pickling an insertion-ordered dict of
    ``encode_array`` tuples is deterministic, so the same training state
    always names the same digest (the property promotion audit records
    rely on)."""
    return hashlib.sha256(pickle.dumps(
        payload, protocol=pickle.HIGHEST_PROTOCOL)).hexdigest()


def provenance(record):
    """The provenance dict of a loaded checkpoint record, or ``None``
    for a pre-provenance snapshot (records stay back/forward readable:
    provenance is an additive key)."""
    if not isinstance(record, dict):
        return None
    return record.get("provenance")


def save_checkpoint(directory, payload, step, keep=3, provenance=None):
    """Atomically write ``payload`` as the step-``step`` checkpoint.

    The bytes are written to a tmp file, fsynced, then ``os.replace``d —
    the checkpoint either exists completely or not at all.  After a
    successful install, older checkpoints beyond ``keep`` (and stray tmp
    files from crashed saves) are pruned.  Returns the final path.

    ``provenance`` (optional dict, e.g. ``{"epoch", "train_run_id"}``)
    is embedded in the record beside an always-computed ``digest`` of
    the payload bytes and the ``step`` — the identity the serving fleet
    and the promotion controller surface."""
    os.makedirs(directory, exist_ok=True)
    final = _ckpt_path(directory, step)
    tmp = final + ".tmp.%d" % os.getpid()
    prov = dict(provenance or {})
    prov.setdefault("step", int(step))
    # a caller may pre-compute a canonicalized digest (the trainer
    # digests gensym-invariant content, so rebuilt-architecture reruns
    # name the same bytes); otherwise digest the payload as-is
    prov.setdefault("digest", payload_digest(payload))
    blob = pickle.dumps({"version": FORMAT_VERSION, "step": int(step),
                         "payload": payload, "provenance": prov},
                        protocol=pickle.HIGHEST_PROTOCOL)
    with open(tmp, "wb") as f:
        # two-part write with a probe between: the chaos harness kills
        # here to prove a torn save never shadows the previous snapshot
        f.write(blob[:len(blob) // 2])
        _chaos.maybe_inject("checkpoint.save")
        f.write(blob[len(blob) // 2:])
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    _prune(directory, keep)
    return final


def _prune(directory, keep):
    entries = list_checkpoints(directory)
    for step, path in entries[:-int(keep)] if keep else []:
        try:
            os.remove(path)
        except OSError:
            pass
    for name in os.listdir(directory):
        if ".tmp." in name and name.split(".tmp.")[0].endswith(CKPT_SUFFIX):
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


def list_checkpoints(directory):
    """[(step, path)] ascending by step; tmp/corrupt-named files ignored."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _NAME_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def load_checkpoint(path):
    """Load one checkpoint file -> ``{"version", "step", "payload"}``.
    Raises on a torn/garbage file (callers fall back to an older one)."""
    with open(path, "rb") as f:
        rec = pickle.load(f)
    if not isinstance(rec, dict) or rec.get("version") != FORMAT_VERSION:
        raise ValueError("not a version-%d checkpoint: %r"
                         % (FORMAT_VERSION, path))
    return rec


def latest_checkpoint(directory):
    """Newest *loadable* checkpoint -> ``(path, record)`` or ``None``.
    A torn newest file (crash between write and replace is impossible,
    but disk corruption is not) falls back to the next-newest."""
    for step, path in reversed(list_checkpoints(directory)):
        try:
            return path, load_checkpoint(path)
        except Exception:
            continue
    return None


# ---------------------------------------------------------------------------
# shard-parallel snapshots: per-rank shard files + a last-committed manifest
# ---------------------------------------------------------------------------
def _shard_name(step, rank, world):
    return "ckpt-%012d.shard-%05d-of-%05d%s" % (int(step), int(rank),
                                                int(world), SHARD_SUFFIX)


def _manifest_path(directory, step):
    return os.path.join(directory,
                        "ckpt-%012d%s" % (int(step), MANIFEST_SUFFIX))


def _atomic_write(path, blob):
    """fsync + rename install of ``blob`` at ``path`` (the snapshot
    discipline): the file exists completely or not at all."""
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save_sharded_checkpoint(directory, payload, shards, step, keep=3,
                            provenance=None):
    """Shard-parallel atomic snapshot: write one shard file per rank,
    then commit the manifest.  Returns the manifest path.

    ``payload`` is the rank-agnostic common state (params, RNG, cursor,
    layout plan); ``shards[r]`` is rank ``r``'s own slice (its ZeRO-1
    optimizer-state shard).  Each shard is fsync+renamed into place
    with its sha256 digest recorded; the manifest — written LAST, same
    discipline — is the commit point: a rank SIGKILLed mid shard write
    (chaos site ``ckpt.shard_write``) leaves only tmp debris and the
    previous complete checkpoint stays the loadable latest.  Pruning
    keeps ``keep`` manifests and only deletes shard files no retained
    manifest references."""
    os.makedirs(directory, exist_ok=True)
    world = len(shards)
    entries = []
    for rank, shard_payload in enumerate(shards):
        blob = pickle.dumps(
            {"version": SHARD_FORMAT_VERSION, "step": int(step),
             "rank": int(rank), "world": int(world),
             "payload": shard_payload},
            protocol=pickle.HIGHEST_PROTOCOL)
        name = _shard_name(step, rank, world)
        # chaos probe: a scheduled fault (SIGKILL while writing shard
        # N) fires before the shard is installed — the atomicity test's
        # injection point
        _chaos.maybe_inject("ckpt.shard_write", ctx=(int(step), rank))
        _atomic_write(os.path.join(directory, name), blob)
        entries.append({"file": name, "rank": int(rank),
                        "digest": hashlib.sha256(blob).hexdigest(),
                        "bytes": len(blob)})
    prov = dict(provenance or {})
    prov.setdefault("step", int(step))
    prov.setdefault("digest", payload_digest(
        {"payload": payload, "shards": [e["digest"] for e in entries]}))
    blob = pickle.dumps(
        {"version": SHARD_FORMAT_VERSION, "step": int(step),
         "world": int(world), "payload": payload, "shards": entries,
         "provenance": prov},
        protocol=pickle.HIGHEST_PROTOCOL)
    final = _manifest_path(directory, step)
    _atomic_write(final, blob)
    _prune_sharded(directory, keep)
    return final


def list_manifests(directory):
    """[(step, manifest_path)] ascending; tmp/garbage names ignored."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def load_sharded_checkpoint(manifest_path):
    """Load + verify one sharded checkpoint -> ``{"version", "step",
    "world", "payload", "shards": [per-rank payloads], "provenance"}``.

    Every shard the manifest references must exist with byte-exact
    digest; a missing or corrupt shard raises
    :class:`ShardIntegrityError` naming the shard and the reason —
    callers (``latest_sharded_checkpoint``) fall back to an older
    complete checkpoint."""
    with open(manifest_path, "rb") as f:
        rec = pickle.load(f)
    if not isinstance(rec, dict) or \
            rec.get("version") != SHARD_FORMAT_VERSION:
        raise ValueError("not a version-%d sharded checkpoint manifest: "
                         "%r" % (SHARD_FORMAT_VERSION, manifest_path))
    directory = os.path.dirname(os.path.abspath(manifest_path))
    shard_payloads = []
    for entry in rec["shards"]:
        path = os.path.join(directory, entry["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise ShardIntegrityError(
                "manifest %s references missing shard %s (rank %d): %s"
                % (os.path.basename(manifest_path), entry["file"],
                   entry.get("rank", -1), e))
        got = hashlib.sha256(blob).hexdigest()
        if got != entry["digest"]:
            raise ShardIntegrityError(
                "shard %s (rank %d) is corrupt: digest %s does not "
                "match the manifest's %s"
                % (entry["file"], entry.get("rank", -1), got[:16],
                   entry["digest"][:16]))
        shard_payloads.append(pickle.loads(blob)["payload"])
    return {"version": rec["version"], "step": int(rec["step"]),
            "world": int(rec["world"]), "payload": rec["payload"],
            "shards": shard_payloads,
            "provenance": rec.get("provenance")}


def latest_sharded_checkpoint(directory):
    """Newest *complete* sharded checkpoint -> ``(manifest_path,
    record)`` or ``None``.  A manifest whose shard set fails the digest
    check (:class:`ShardIntegrityError`) falls back to the next-newest
    — the last-committed-manifest-wins semantics."""
    for step, path in reversed(list_manifests(directory)):
        try:
            return path, load_sharded_checkpoint(path)
        except Exception:
            continue
    return None


def _prune_sharded(directory, keep):
    """Drop manifests beyond ``keep`` plus every shard file no retained
    manifest references, and tmp debris from crashed saves."""
    manifests = list_manifests(directory)
    dropped = manifests[:-int(keep)] if keep else []
    kept = manifests[len(dropped):]
    referenced = set()
    for _, path in kept:
        try:
            with open(path, "rb") as f:
                rec = pickle.load(f)
            for entry in rec.get("shards", []):
                referenced.add(entry["file"])
        except Exception:
            continue
    for _, path in dropped:
        try:
            os.remove(path)
        except OSError:
            pass
    for name in os.listdir(directory):
        full = os.path.join(directory, name)
        if name.endswith(SHARD_SUFFIX) and name not in referenced:
            try:
                os.remove(full)
            except OSError:
                pass
        elif ".tmp." in name and (
                name.split(".tmp.")[0].endswith(SHARD_SUFFIX)
                or name.split(".tmp.")[0].endswith(MANIFEST_SUFFIX)):
            try:
                os.remove(full)
            except OSError:
                pass
