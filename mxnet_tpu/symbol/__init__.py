"""`mx.sym` — symbolic graph composition namespace.

The op namespace is code-generated from the same registry as `mx.nd`
(reference: python/mxnet/symbol/register.py), so every imperative op has a
symbolic twin.
"""
from __future__ import annotations

import sys as _sys
import types as _types

from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     AttrScope, NameManager, _sym_invoke)
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


def _make_sym_func(op, name):
    def fn(*args, **kwargs):
        return _sym_invoke(op, name, args, kwargs)
    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = op.doc or ("%s symbol (TPU-native)." % name)
    return fn


_internal = _types.ModuleType(__name__ + "._internal")
contrib = _types.ModuleType(__name__ + ".contrib")
linalg = _types.ModuleType(__name__ + ".linalg")
random = _types.ModuleType(__name__ + ".random")
_this = _sys.modules[__name__]

for _name in _reg.list_ops():
    _op = _reg.get(_name)
    _f = _make_sym_func(_op, _name)
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], _f)
    elif _name.startswith("_linalg_"):
        setattr(linalg, _name[len("_linalg_"):], _f)
    elif _name.startswith("_random_"):
        setattr(random, _name[len("_random_"):], _f)
    if _name.startswith("_"):
        setattr(_internal, _name, _f)
    elif not hasattr(_this, _name):
        setattr(_this, _name, _f)
    if not hasattr(_internal, _name):
        setattr(_internal, _name, _f)

_sys.modules[__name__ + "._internal"] = _internal
_sys.modules[__name__ + ".contrib"] = contrib
_sys.modules[__name__ + ".linalg"] = linalg
_sys.modules[__name__ + ".random"] = random


def zeros(shape, dtype=None, **kwargs):
    return getattr(_internal, "_zeros")(shape=shape, dtype=dtype or "float32", **kwargs)


def ones(shape, dtype=None, **kwargs):
    return getattr(_internal, "_ones")(shape=shape, dtype=dtype or "float32", **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, **kwargs):
    return getattr(_internal, "_arange")(start=start, stop=stop, step=step,
                                         repeat=repeat,
                                         dtype=dtype or "float32", **kwargs)
