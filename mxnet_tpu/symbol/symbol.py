"""Symbol: declarative graph composition, the TPU-native `mx.sym`.

Reference: ``python/mxnet/symbol/symbol.py`` (3.9k LoC) over the nnvm graph
IR.  The reference Symbol is a handle to an nnvm node DAG; binding runs the
GraphExecutor (``src/executor/graph_executor.cc:514``) which builds the
backward graph, plans memory and attaches engine ops.  Here the DAG is a
tiny Python node list evaluated as a pure jax function — ``jax.jit`` is the
memory planner/executor, ``jax.vjp`` is the ``pass::Gradient`` analogue and
``jax.eval_shape`` replaces the shape/type fixpoint passes
(``src/executor/infer_graph_attr_pass.cc``).

JSON serialization keeps the reference's on-disk schema
(nodes/arg_nodes/heads, ``save``/``load``) so checkpoints remain
tool-compatible.
"""
from __future__ import annotations

import json

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import MXNetError, np_dtype
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


# ---------------------------------------------------------------------------
# Name manager: default names conv0, conv1, ... per op family
# (reference: python/mxnet/name.py NameManager)
# ---------------------------------------------------------------------------
class NameManager:
    _current = None

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        hint = hint.lower().lstrip("_")
        i = self._counter.get(hint, 0)
        self._counter[hint] = i + 1
        return "%s%d" % (hint, i)


NameManager._current = NameManager()


class AttrScope:
    """Scoped symbol attributes; carries ``ctx_group`` / ``__layout__`` etc.
    (reference: python/mxnet/attribute.py — used for group2ctx model
    parallelism; here ctx_group maps to sharding annotations)."""
    _current = None

    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}
        self._old = None

    def get(self, user_attrs):
        out = dict(self._attrs)
        if user_attrs:
            out.update(user_attrs)
        return out

    def __enter__(self):
        self._old = AttrScope._current
        merged = dict(self._old._attrs) if self._old else {}
        merged.update(self._attrs)
        self._attrs = merged
        AttrScope._current = self
        return self

    def __exit__(self, *a):
        AttrScope._current = self._old


AttrScope._current = AttrScope()


class _Node:
    """One graph node.  ``op is None`` → variable (nnvm "null" op)."""
    __slots__ = ("op", "name", "attrs", "inputs", "_is_aux")

    def __init__(self, op, name, attrs=None, inputs=(), is_aux=False):
        self.op = op
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.inputs = list(inputs)   # list of (node, out_index)
        self._is_aux = is_aux

    def __repr__(self):
        return "_Node(%s, %s)" % (self.op or "null", self.name)


def _topo(heads):
    """Post-order DFS over (node) from head entries."""
    seen = set()
    order = []
    stack = [e[0] for e in heads]
    path = []
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        # iterative post-order
        path.append((node, False))
        while path:
            n, expanded = path.pop()
            if id(n) in seen:
                continue
            if expanded:
                seen.add(id(n))
                order.append(n)
            else:
                path.append((n, True))
                for (child, _) in reversed(n.inputs):
                    if id(child) not in seen:
                        path.append((child, False))
    return order


# Shape rules for ops whose parameter shapes must be inferred from the data
# shape (the reference runs a bidirectional fixpoint; forward + these local
# rules covers every bind scenario in practice).
def _conv_param_shapes(attrs, dshape):
    kernel = attrs.get("kernel", ())
    num_filter = int(attrs.get("num_filter"))
    num_group = int(attrs.get("num_group", 1))
    if attrs.get("layout") in ("NWC", "NHWC", "NDHWC"):
        # channels-last weight layout is (O, *kernel, I/group)
        w = (num_filter,) + tuple(kernel) + (dshape[-1] // num_group,)
    else:
        w = (num_filter, dshape[1] // num_group) + tuple(kernel)
    shapes = {"weight": w}
    if not attrs.get("no_bias", False):
        shapes["bias"] = (num_filter,)
    return shapes


def _deconv_param_shapes(attrs, dshape):
    kernel = attrs.get("kernel", ())
    num_filter = int(attrs.get("num_filter"))
    num_group = int(attrs.get("num_group", 1))
    w = (dshape[1], num_filter // num_group) + tuple(kernel)
    shapes = {"weight": w}
    if not attrs.get("no_bias", True):
        shapes["bias"] = (num_filter,)
    return shapes


def _fc_param_shapes(attrs, dshape):
    num_hidden = int(attrs.get("num_hidden"))
    flatten = attrs.get("flatten", True)
    in_dim = 1
    if flatten:
        for d in dshape[1:]:
            in_dim *= d
    else:
        in_dim = dshape[-1]
    shapes = {"weight": (num_hidden, in_dim)}
    if not attrs.get("no_bias", False):
        shapes["bias"] = (num_hidden,)
    return shapes


def _bn_param_shapes(attrs, dshape):
    axis = int(attrs.get("axis", 1))
    c = dshape[axis]
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,), "moving_var": (c,)}


def _in_param_shapes(attrs, dshape):
    c = dshape[1]
    return {"gamma": (c,), "beta": (c,)}


def _ln_param_shapes(attrs, dshape):
    axis = int(attrs.get("axis", -1))
    c = dshape[axis]
    return {"gamma": (c,), "beta": (c,)}


def _embed_param_shapes(attrs, dshape):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _rnn_param_shapes(attrs, dshape):
    # dshape: (seq_len, batch, input_size); single flat parameter vector,
    # layout matching ops/rnn.py pack order (reference: rnn-inl.h:49).
    from ..ops import rnn as _rnn_ops
    return {"parameters": (_rnn_ops.rnn_param_size(
        int(attrs["state_size"]), dshape[2], int(attrs.get("num_layers", 1)),
        attrs.get("mode", "lstm"), attrs.get("bidirectional", False)),),
        "state": _rnn_ops.rnn_state_shape(attrs, dshape),
        "state_cell": _rnn_ops.rnn_state_shape(attrs, dshape)}


# label-shape rules: MXNet's bidirectional fixpoint infers label shapes from
# the data input of output heads; these local rules cover that direction.
def _softmax_label_shape(attrs, dshape):
    if _reg.canonicalize(attrs.get("multi_output", False)):
        return (dshape[0],) + tuple(dshape[2:])
    return tuple(dshape[:-1])


_LABEL_SHAPE_RULES = {
    "SoftmaxOutput": _softmax_label_shape,
    "SVMOutput": lambda attrs, d: (d[0],),
    "LinearRegressionOutput": lambda attrs, d: tuple(d),
    "MAERegressionOutput": lambda attrs, d: tuple(d),
    "LogisticRegressionOutput": lambda attrs, d: tuple(d),
}

_PARAM_SHAPE_RULES = {
    "Convolution": _conv_param_shapes,
    "Deconvolution": _deconv_param_shapes,
    "FullyConnected": _fc_param_shapes,
    "BatchNorm": _bn_param_shapes,
    "InstanceNorm": _in_param_shapes,
    "LayerNorm": _ln_param_shapes,
    "Embedding": _embed_param_shapes,
    "RNN": _rnn_param_shapes,
}


class Symbol:
    """Immutable handle to a list of output entries of a graph."""
    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)

    # -- introspection -----------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def _nodes(self):
        return _topo(self._outputs)

    def list_arguments(self):
        return [n.name for n in self._nodes() if n.op is None and not n._is_aux]

    def list_auxiliary_states(self):
        return [n.name for n in self._nodes() if n.op is None and n._is_aux]

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.op is None:
                out.append(node.name)
                continue
            op = _reg.get(node.op)
            n = op.n_outputs(_attr_params(op, node.attrs))
            out.append("%s_output" % node.name if n == 1
                       else "%s_output%d" % (node.name, idx))
        return out

    def list_inputs(self):
        return [n.name for n in self._nodes() if n.op is None]

    def get_internals(self):
        entries = []
        for n in self._nodes():
            if n.op is None:
                entries.append((n, 0))
            else:
                op = _reg.get(n.op)
                for i in range(op.n_outputs(_attr_params(op, n.attrs))):
                    entries.append((n, i))
        return Symbol(entries)

    def get_children(self):
        kids = []
        for node, _ in self._outputs:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            matches = [i for i, n in enumerate(names)
                       if n == index or n.rsplit("_output", 1)[0] == index]
            if len(matches) != 1:
                raise ValueError("cannot resolve output %r (candidates %r)"
                                 % (index, names))
            index = matches[0]
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (Symbol([e]) for e in self._outputs)

    def attr(self, key):
        if len(self._outputs) == 1:
            return self._outputs[0][0].attrs.get(key)
        return None

    def attr_dict(self):
        out = {}
        for n in self._nodes():
            if n.attrs:
                out[n.name] = {k: str(v) for k, v in n.attrs.items()
                               if not k.startswith("__param")}
        return out

    def _set_attr(self, **kwargs):
        for n in self._outputs:
            n[0].attrs.update({k: str(v) for k, v in kwargs.items()})

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else
                                ", ".join(self.list_outputs()))

    # -- composition -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: replace free variables of this symbol with other symbols
        (reference: symbol.py Symbol.__call__/_compose)."""
        if args:
            raise TypeError("composition supports keyword arguments only")
        mapping = {}
        for name, s in kwargs.items():
            if not isinstance(s, Symbol):
                raise TypeError("can only compose with Symbols")
            mapping[name] = s._outputs[0]
        memo = {}

        def clone(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.op is None and node.name in mapping:
                sub = mapping[node.name][0]
                memo[id(node)] = sub
                return sub
            new = _Node(node.op, node.name, node.attrs,
                        [(clone(c), i) for c, i in node.inputs], node._is_aux)
            memo[id(node)] = new
            return new

        return Symbol([(clone(n), i) for n, i in self._outputs])

    # -- arithmetic sugar --------------------------------------------------
    def __add__(self, o):
        return _binary(self, o, "_plus", "_plus_scalar")

    def __radd__(self, o):
        return self.__add__(o)

    def __sub__(self, o):
        return _binary(self, o, "_minus", "_minus_scalar")

    def __rsub__(self, o):
        return _binary(self, o, None, "_rminus_scalar")

    def __mul__(self, o):
        return _binary(self, o, "_mul", "_mul_scalar")

    def __rmul__(self, o):
        return self.__mul__(o)

    def __truediv__(self, o):
        return _binary(self, o, "_div", "_div_scalar")

    def __rtruediv__(self, o):
        return _binary(self, o, None, "_rdiv_scalar")

    def __pow__(self, o):
        return _binary(self, o, "_power", "_power_scalar")

    def __neg__(self):
        return _create("_neg", [self], {}, None)

    def __eq__(self, o):  # noqa: matching reference semantics
        if isinstance(o, (Symbol, int, float)):
            return _binary(self, o, "_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return _binary(self, o, "_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, o):
        return _binary(self, o, "_greater", "_greater_scalar")

    def __ge__(self, o):
        return _binary(self, o, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return _binary(self, o, "_lesser", "_lesser_scalar")

    def __le__(self, o):
        return _binary(self, o, "_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __getattr__(self, name):
        # method-style op calls: sym.reshape(...), sym.sum(...) — resolved
        # from the registry like the reference's generated methods.
        if name.startswith("_"):
            raise AttributeError(name)
        if _reg.exists(name):
            def method(*args, **kwargs):
                return _sym_invoke(_reg.get(name), name, (self,) + args, kwargs)
            return method
        raise AttributeError("Symbol has no attribute %r" % name)

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self._infer_shape_impl(
            False, *args, **kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        if args:
            kwargs = dict(zip(self.list_arguments(), args), **kwargs)
        known = {k: tuple(v) for k, v in kwargs.items() if v is not None}
        shapes, ok = _infer_entry_shapes(self._outputs, known, {})
        arg_shapes, aux_shapes = [], []
        for n in self._nodes():
            if n.op is None:
                s = shapes.get((id(n), 0))
                s = tuple(s.shape) if s is not None else None
                (aux_shapes if n._is_aux else arg_shapes).append(s)
        out_shapes = []
        for e in self._outputs:
            s = shapes.get((id(e[0]), e[1]))
            out_shapes.append(tuple(s.shape) if s is not None else None)
        if not ok and not partial:
            return None, None, None
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Dtype propagation: result_type promotion per node, Cast/argmax
        overriding (the reference runs an nnvm fixpoint; promotion matches
        its rules for every registered op)."""
        if args:
            kwargs = dict(zip(self.list_arguments(), args), **kwargs)
        dtypes = {k: _np.dtype(np_dtype(v)) for k, v in kwargs.items()
                  if v is not None}
        _INT_OUT = {"argmax", "argmin", "argsort", "topk", "one_hot",
                    "shape_array", "size_array"}
        env = {}
        for n in self._nodes():
            if n.op is None:
                dt = dtypes.get(n.name)
                if dt is None and "__dtype__" in n.attrs:
                    dt = _np.dtype(n.attrs["__dtype__"])
                env[id(n)] = dt if dt is not None else _np.dtype(_np.float32)
                continue
            if n.op == "Cast" or n.op == "cast":
                env[id(n)] = _np.dtype(np_dtype(
                    _reg.canonicalize(n.attrs.get("dtype", "float32"))))
                continue
            ins = [env.get(id(c)) for c, _ in n.inputs]
            ins = [d for d in ins if d is not None]
            env[id(n)] = _np.dtype(_np.result_type(*ins)) if ins else \
                _np.dtype(_np.float32)
        args_t, aux_t = [], []
        for n in self._nodes():
            if n.op is None:
                (aux_t if n._is_aux else args_t).append(env.get(id(n)))
        outs_t = [env.get(id(e[0])) for e in self._outputs]
        return args_t, outs_t, aux_t

    # -- serialization (reference JSON schema) ----------------------------
    def tojson(self):
        nodes = self._nodes()
        index = {id(n): i for i, n in enumerate(nodes)}
        jnodes, arg_nodes = [], []
        for i, n in enumerate(nodes):
            if n.op is None:
                arg_nodes.append(i)
            jnodes.append({
                "op": n.op if n.op else "null",
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[index[id(c)], oi, 0] for c, oi in n.inputs],
            })
        heads = [[index[id(n)], oi, 0] for n, oi in self._outputs]
        return json.dumps({
            "nodes": jnodes, "arg_nodes": arg_nodes, "heads": heads,
            "attrs": {"mxnet_version": ["int", 10300],
                      "framework": ["str", "mxnet_tpu"]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- evaluation / binding ---------------------------------------------
    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, lint=False, **kwargs):
        """Allocate argument/grad/aux arrays from inferred shapes and bind
        (reference: symbol.py:1289 → MXExecutorSimpleBindEx).  ``lint=True``
        runs the mxlint graph pass before binding (error findings raise)."""
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    type_dict=type_dict, shapes=kwargs,
                                    lint=lint)

    def lint(self, shapes=None, type_dict=None, disable=(),
             check_consts=True):
        """Static graph lint (mxnet_tpu.analysis): dead outputs, gradient
        cuts, aux misuse, float64 promotion, recompile traps, oversized
        constants.  Returns a list of ``Finding`` records."""
        from ..analysis import lint_symbol
        return lint_symbol(self, shapes=shapes, type_dict=type_dict,
                           disable=disable, check_consts=check_consts)

    def cost_report(self, shapes, type_dict=None, train=False,
                    host_names=None):
        """Static cost/memory model of this graph's forward program
        (mxnet_tpu.analysis.cost — mxcost): FLOPs, bytes, host↔device
        transfer, liveness-based peak HBM.  Nothing executes or
        compiles.  ``shapes`` must make the graph inferable (same
        contract as ``lint``'s constant check); names in ``shapes`` are
        treated as host-fed per call unless ``host_names`` overrides.
        Returns a ``CostReport`` or None if the graph does not trace."""
        from ..analysis.cost import analyze_symbol
        return analyze_symbol(self, shapes=shapes, type_dict=type_dict,
                              train=train, host_names=host_names)

    def fusion_report(self, shapes, type_dict=None, train=False):
        """mxfuse fusion-candidate report of this graph's forward
        program (mxnet_tpu.analysis.fusion): the cost tape segmented
        into fusable elementwise/broadcast/cast/reduction-epilogue
        chains ranked by modeled bytes-saved-if-fused (docs/fusion.md).
        Same tracing contract as ``cost_report``; returns a
        ``FusionReport`` or None if the graph does not trace."""
        from ..analysis.fusion import fusion_for_symbol
        return fusion_for_symbol(self, shapes=shapes,
                                 type_dict=type_dict, train=train)

    def shard_report(self, shapes, mesh_axes, in_specs=None,
                     type_dict=None, train=False, data_axis="data"):
        """mxshard global-view sharding propagation of this graph's
        forward program (mxnet_tpu.analysis.shard_prop): given a
        declared mesh (``mesh_axes``: {axis: size} — no devices) and
        per-argument ``PartitionSpec``s, returns a ``ShardReport`` with
        the inferred collective schedule (partial-sum psums from
        contracted sharded dims) and any forced reshards.  Defaults:
        the names in ``shapes`` (the batch inputs) shard dim 0 over
        ``data_axis`` when the mesh has it; parameters replicate.
        Returns None when the graph does not trace."""
        from ..analysis import shard_prop as _sp

        known = {k: tuple(v) for k, v in (shapes or {}).items()
                 if v is not None}
        tdict = {k: _np.dtype(v) for k, v in (type_dict or {}).items()}
        entry_shapes, ok = _infer_entry_shapes(self._outputs, known,
                                               tdict)
        if not ok:
            return None
        args, aux = {}, {}
        for n in self._nodes():
            if n.op is not None:
                continue
            s = entry_shapes.get((id(n), 0))
            if s is None:
                return None
            (aux if n._is_aux else args)[n.name] = jax.ShapeDtypeStruct(
                tuple(s.shape), s.dtype)
        graph_fn = make_graph_fn(self, train=train)
        try:
            closed = jax.make_jaxpr(graph_fn)(
                args, aux, jax.random.PRNGKey(0))
        except Exception:
            return None
        mesh = _sp.MeshSpec(mesh_axes)
        in_specs = dict(in_specs or {})
        from jax.sharding import PartitionSpec as _P
        flat_specs = []
        for name in sorted(args) + sorted(aux):
            if name in in_specs:
                flat_specs.append(in_specs[name])
            elif name in known and data_axis in mesh:
                flat_specs.append(_P(data_axis))
            else:
                flat_specs.append(None)
        flat_specs.append(None)     # the PRNG key
        return _sp.propagate(closed, mesh, flat_specs,
                             subject=self.name or "<symbol>")

    # gradient of this symbol's outputs — handled inside Executor via vjp
    def grad(self, wrt):
        raise NotImplementedError(
            "symbolic grad graphs are implicit: bind() compiles the vjp")


# ---------------------------------------------------------------------------
# shape/type propagation over the DAG using jax.eval_shape per node
# ---------------------------------------------------------------------------
def _attr_params(op, attrs):
    params = {k: _reg.canonicalize(v) for k, v in attrs.items()
              if not k.startswith("__") and k not in _EXECUTOR_ATTRS}
    if op is not None and op.needs_train:
        params["_train"] = False
    return params


def _infer_entry_shapes(heads, known_shapes, known_dtypes, need_shapes=True):
    """Forward shape/dtype propagation.  Returns ({(node_id,out_idx):
    ShapeDtypeStruct}, fully_known)."""
    shapes = {}
    ok = True
    order = _topo(heads)
    node_by_name = {n.name: n for n in order if n.op is None}
    for n in order:
        if n.op is None:
            shp = known_shapes.get(n.name)
            dt = known_dtypes.get(n.name, _np.float32)
            if shp is None and "__shape__" in n.attrs:
                shp = tuple(_reg.canonicalize(n.attrs["__shape__"]))
            if shp is None and need_shapes:
                continue
            shapes[(id(n), 0)] = jax.ShapeDtypeStruct(
                tuple(shp) if shp else (), _np.dtype(dt))
            continue
        op = _reg.get(n.op)
        params = _attr_params(op, n.attrs)
        # derive missing parameter-variable shapes from the data input
        rule = _PARAM_SHAPE_RULES.get(n.op)
        if rule is not None:
            d0 = shapes.get((id(n.inputs[0][0]), n.inputs[0][1]))
            if d0 is not None:
                try:
                    derived = rule(params, tuple(d0.shape))
                except (KeyError, TypeError, IndexError):
                    derived = {}
                for (child, _) in n.inputs[1:]:
                    if child.op is None and (id(child), 0) not in shapes:
                        suffix = child.name.rsplit("_", 1)[-1]
                        # match by arg suffix: conv0_weight → weight
                        for pname, pshape in derived.items():
                            if suffix == pname or child.name.endswith(pname):
                                if pshape is not None:
                                    shapes[(id(child), 0)] = jax.ShapeDtypeStruct(
                                        tuple(pshape), _np.float32)
                                break
        lrule = _LABEL_SHAPE_RULES.get(n.op)
        if lrule is not None and len(n.inputs) > 1:
            d0 = shapes.get((id(n.inputs[0][0]), n.inputs[0][1]))
            lab = n.inputs[1][0]
            if d0 is not None and lab.op is None and (id(lab), 0) not in shapes:
                shapes[(id(lab), 0)] = jax.ShapeDtypeStruct(
                    lrule(n.attrs, tuple(d0.shape)), _np.float32)
        in_structs = []
        missing = False
        for (child, oi) in n.inputs:
            s = shapes.get((id(child), oi))
            if s is None:
                missing = True
                break
            in_structs.append(s)
        if missing:
            ok = False
            continue
        try:
            out = jax.eval_shape(lambda *xs: op.fn(*xs, **params), *in_structs)
        except Exception:
            ok = False
            continue
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, o in enumerate(outs):
            shapes[(id(n), i)] = o
    if need_shapes:
        for n in order:
            if n.op is None and (id(n), 0) not in shapes:
                ok = False
    return shapes, ok


# ---------------------------------------------------------------------------
# graph evaluation — shared by Executor and Module
# ---------------------------------------------------------------------------
# attrs consumed by the executor (placement/learning-rate metadata), never
# forwarded to op kernels — the reference strips these in the same way
# (nnvm attrs vs op params)
_EXECUTOR_ATTRS = frozenset({
    "ctx_group", "lr_mult", "wd_mult", "force_mirroring", "mirror_stage",
})


def make_graph_fn(symbol, train, sharding_map=None):
    """Build fn(arg_dict, aux_dict) -> (list outputs, new_aux_dict) — a pure
    jax function over the DAG, suitable for jit/vjp.  The reference analogue
    is GraphExecutor::RunOps over cached engine ops; XLA compiles the whole
    thing into one program instead.

    ``sharding_map``: {node_name: jax sharding} — outputs of those nodes
    get ``lax.with_sharding_constraint``, the GSPMD consumption of the
    reference's ``ctx_group``/PlaceDevice pass
    (src/executor/graph_executor.cc:408)."""
    order = symbol._nodes()
    heads = symbol._outputs

    def graph_fn(arg_dict, aux_dict, rng_key):
        """rng_key: PRNG key threaded as a real argument so stochastic ops
        (Dropout, random samplers) stay pure under jit (see _rng.py)."""
        from .. import _rng
        with _rng.trace_scope(rng_key):
            return _graph_eval(arg_dict, aux_dict)

    def _graph_eval(arg_dict, aux_dict):
        import jax as _jax
        env = {}
        new_aux = dict(aux_dict)
        for n in order:
            if n.op is None:
                if n._is_aux:
                    env[(id(n), 0)] = new_aux[n.name]
                else:
                    env[(id(n), 0)] = arg_dict[n.name]
                continue
            op = _reg.get(n.op)
            params = {k: _reg.canonicalize(v) for k, v in n.attrs.items()
                      if not k.startswith("__") and k not in _EXECUTOR_ATTRS}
            if op.needs_train:
                params["_train"] = train
            ins = [env[(id(c), oi)] for c, oi in n.inputs]
            out = op.fn(*ins, **params)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            if sharding_map and n.name in sharding_map:
                mesh, spec = sharding_map[n.name]
                from jax.sharding import NamedSharding as _NS
                from ..executor import _fit_spec as _fit
                outs = tuple(_jax.lax.with_sharding_constraint(
                    o, _NS(mesh, _fit(spec, o.shape, mesh))) for o in outs)
            for i, o in enumerate(outs):
                env[(id(n), i)] = o
            if train and op.aux_update is not None and not params.get("use_global_stats"):
                updates = op.aux_update(ins, outs, params)
                for idx, val in updates.items():
                    child = n.inputs[idx][0]
                    if child.op is None and child._is_aux:
                        new_aux[child.name] = val
        return [env[(id(n), oi)] for n, oi in heads], new_aux

    return graph_fn


# ---------------------------------------------------------------------------
# symbol-side op invocation (the generated sym.* functions)
# ---------------------------------------------------------------------------
def _sym_invoke(op, op_name, args, kwargs):
    name = kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)
    name = NameManager._current.get(name, op_name)

    sym_inputs = []   # (argname_or_None, Symbol)
    params = {}

    if op.arg_names == ["args"]:
        # variadic (Concat / add_n / ...)
        flat = []
        for a in args:
            if isinstance(a, (list, tuple)):
                flat.extend(a)
            else:
                flat.append(a)
        for a in flat:
            if not isinstance(a, Symbol):
                raise TypeError("%s expects Symbols, got %r" % (op_name, type(a)))
            sym_inputs.append((None, a))
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_inputs.append((k, v))
            else:
                params[k] = v
        entries = [s._outputs[0] for _, s in sym_inputs]
    else:
        names = list(op.arg_names)
        for idx, aux_name in sorted(op.aux.items()):
            names.append(aux_name)
        slots = {}
        for i, a in enumerate(args):
            if isinstance(a, Symbol):
                slots[names[i]] = a
            elif isinstance(a, str):
                # the classic misuse (reference raises TypeError when a
                # non-Symbol lands in a tensor slot); scalar positionals
                # are still accepted as params for nd/sym API symmetry
                raise TypeError(
                    "%s expects Symbol for argument %r, got str %r"
                    % (op_name, names[i], a))
            else:
                params[names[i]] = a
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                slots[k] = v
            else:
                params[k] = v
        aux_names = set(op.aux.values())
        entries = []
        no_bias = params.get("no_bias", _reg.canonicalize(params.get("no_bias", False)))
        optional = op.optional(_reg.canonicalize_kwargs(params))
        # auto-created variables inherit the scope attrs (ctx_group,
        # lr_mult, ...) exactly as the reference's AttrScope does
        scope_attrs = AttrScope._current.get({})
        for an in names:
            if an in slots:
                entries.append(slots[an]._outputs[0])
            else:
                if an in optional:
                    continue
                if an == "bias" and _reg.canonicalize(no_bias):
                    continue
                if an in ("label",) and an not in slots:
                    # SoftmaxOutput etc: auto label variable named <name>_label
                    vnode = _Node(None, "%s_%s" % (name, an),
                                  dict(scope_attrs))
                    entries.append((vnode, 0))
                    continue
                # auto-create parameter/aux variable <name>_<argname>
                if an == names[0]:
                    vnode = _Node(None, "%s_%s" % (name, an),
                                  dict(scope_attrs))
                else:
                    vnode = _Node(None, "%s_%s" % (name, an),
                                  dict(scope_attrs), is_aux=an in aux_names)
                entries.append((vnode, 0))

    attrs = AttrScope._current.get(attr or {})
    for k, v in params.items():
        if v is not None:
            attrs[k] = v
    node = _Node(op_name, name, attrs, entries)
    n_out = op.n_outputs(_attr_params(op, attrs))
    if n_out == 1:
        return Symbol([(node, 0)])
    return Symbol([(node, i) for i in range(n_out)])


def _binary(lhs, rhs, op_name, scalar_op_name):
    if isinstance(rhs, Symbol):
        if op_name is None:
            raise TypeError("unsupported operand order")
        return _create(op_name, [lhs, rhs], {}, None)
    return _create(scalar_op_name, [lhs], {"scalar": float(rhs)}, None)


def _create(op_name, sym_args, params, name):
    op = _reg.get(op_name)
    kwargs = dict(params)
    if name is not None:
        kwargs["name"] = name
    return _sym_invoke(op, op_name, tuple(sym_args), kwargs)


# ---------------------------------------------------------------------------
# public constructors
# ---------------------------------------------------------------------------
def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    if not isinstance(name, str):
        raise TypeError("Variable name must be a string")
    attrs = AttrScope._current.get(attr or {})
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = _np.dtype(np_dtype(dtype)).name
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        attrs["__init__"] = init.dumps() if hasattr(init, "dumps") else str(init)
    attrs.update({k: str(v) for k, v in kwargs.items()})
    return Symbol([(_Node(None, name, attrs), 0)])


var = Variable


def Group(symbols):
    entries = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Group expects Symbols")
        entries.extend(s._outputs)
    return Symbol(entries)


def load_json(json_str):
    g = json.loads(json_str)
    nodes = []
    aux_names = set()
    # first pass: find aux slots from op metadata
    for jn in g["nodes"]:
        if jn["op"] != "null":
            op = _reg.get(jn["op"])
            for pos, aux_name in op.aux.items():
                if pos < len(jn["inputs"]):
                    aux_names.add(jn["inputs"][pos][0])
    for i, jn in enumerate(g["nodes"]):
        attrs = jn.get("attrs") or jn.get("param") or {}
        node = _Node(None if jn["op"] == "null" else jn["op"],
                     jn["name"], attrs,
                     [(nodes[ci], oi) for ci, oi, _ in jn["inputs"]],
                     is_aux=i in aux_names)
        nodes.append(node)
    return Symbol([(nodes[ni], oi) for ni, oi, _ in g["heads"]])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
