"""Symbolic model builders.

Reference: the symbol-API model definitions the reference ships as
examples (``example/image-classification/symbols/resnet.py``) — used by
the Module training path, the quantization driver (int8 graph rewrite
needs a Symbol graph) and the legacy FeedForward API.  Architecture is
the same ResNet v1 family as the Gluon zoo.
"""
from __future__ import annotations

from . import symbol as _sym_mod
from .symbol import var, Group  # noqa: F401

__all__ = ["resnet_symbol"]


def _sym():
    from .. import symbol
    return symbol


_SPEC = {
    18: ("basic", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottleneck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottleneck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottleneck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


def _conv_bn_act(sym, data, channels, kernel, stride, pad, name, act=True,
                 layout="NCHW"):
    out = sym.Convolution(data, kernel=kernel, stride=stride, pad=pad,
                          num_filter=channels, no_bias=True, layout=layout,
                          name=name + "_conv")
    out = sym.BatchNorm(out, fix_gamma=False, name=name + "_bn",
                        axis=3 if layout == "NHWC" else 1)
    if act:
        out = sym.Activation(out, act_type="relu", name=name + "_relu")
    return out


def _basic_block(sym, data, channels, stride, downsample, name,
                 layout="NCHW"):
    body = _conv_bn_act(sym, data, channels, (3, 3), (stride, stride),
                        (1, 1), name + "_a", layout=layout)
    body = _conv_bn_act(sym, body, channels, (3, 3), (1, 1), (1, 1),
                        name + "_b", act=False, layout=layout)
    shortcut = data
    if downsample:
        shortcut = _conv_bn_act(sym, data, channels, (1, 1),
                                (stride, stride), (0, 0), name + "_down",
                                act=False, layout=layout)
    return sym.Activation(body + shortcut, act_type="relu",
                          name=name + "_out")


def _bottleneck_block(sym, data, channels, stride, downsample, name,
                      layout="NCHW"):
    mid = channels // 4
    body = _conv_bn_act(sym, data, mid, (1, 1), (stride, stride), (0, 0),
                        name + "_a", layout=layout)
    body = _conv_bn_act(sym, body, mid, (3, 3), (1, 1), (1, 1), name + "_b",
                        layout=layout)
    body = _conv_bn_act(sym, body, channels, (1, 1), (1, 1), (0, 0),
                        name + "_c", act=False, layout=layout)
    shortcut = data
    if downsample:
        shortcut = _conv_bn_act(sym, data, channels, (1, 1),
                                (stride, stride), (0, 0), name + "_down",
                                act=False, layout=layout)
    return sym.Activation(body + shortcut, act_type="relu",
                          name=name + "_out")


def resnet_symbol(num_layers=50, num_classes=1000, thumbnail=False,
                  layout="NCHW"):
    """ResNet v1 as a Symbol graph (reference:
    example/image-classification/symbols/resnet.py; architecture matches
    gluon/model_zoo/vision/resnet.py ResNetV1).  ``layout="NHWC"`` emits
    the channels-last graph — the TPU-native tiling — with OHWI weights."""
    sym = _sym()
    if num_layers not in _SPEC:
        raise ValueError("unsupported depth %r" % (num_layers,))
    kind, layers, channels = _SPEC[num_layers]
    block = _basic_block if kind == "basic" else _bottleneck_block

    data = sym.Variable("data")
    if thumbnail:
        body = sym.Convolution(data, kernel=(3, 3), stride=(1, 1),
                               pad=(1, 1), num_filter=channels[0],
                               no_bias=True, layout=layout,
                               name="stem_conv")
    else:
        body = _conv_bn_act(sym, data, channels[0], (7, 7), (2, 2), (3, 3),
                            "stem", layout=layout)
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", layout=layout, name="stem_pool")
    in_c = channels[0]
    for i, n in enumerate(layers):
        stride = 1 if i == 0 else 2
        body = block(sym, body, channels[i + 1], stride,
                     channels[i + 1] != in_c, "stage%d_unit1" % (i + 1),
                     layout=layout)
        for j in range(n - 1):
            body = block(sym, body, channels[i + 1], 1, False,
                         "stage%d_unit%d" % (i + 1, j + 2), layout=layout)
        in_c = channels[i + 1]
    pool = sym.Pooling(body, global_pool=True, pool_type="avg",
                       layout=layout, name="global_pool")
    flat = sym.Flatten(pool, name="flatten")
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
