"""``mx.nd`` — the imperative NDArray namespace.

Reference: ``python/mxnet/ndarray/``.  Functions are generated from the op
registry (see register.py); creation helpers mirror ndarray.py's public API.
"""
from __future__ import annotations

import sys
import types

import numpy as _np
import jax.numpy as _jnp

from ..base import np_dtype
from ..context import current_context
from ..ops import registry as _reg
from .ndarray import NDArray, array, empty, concatenate, invoke, imperative_invoke

# generated namespace -------------------------------------------------------
_internal = types.ModuleType(__name__ + "._internal")
sys.modules[_internal.__name__] = _internal

from . import register as _register  # noqa: E402

_register.populate(sys.modules[__name__], _internal)

# `nd.contrib` / `nd.linalg` / `nd.random` sub-namespaces: _contrib_*-style
# registered names exposed with the prefix stripped (reference:
# python/mxnet/ndarray/contrib.py generated namespaces)
contrib = types.ModuleType(__name__ + ".contrib")
linalg = types.ModuleType(__name__ + ".linalg")
sys.modules[contrib.__name__] = contrib
sys.modules[linalg.__name__] = linalg
for _name in _reg.list_ops():
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):],
                getattr(_internal, _name))
    elif _name.startswith("_linalg_"):
        setattr(linalg, _name[len("_linalg_"):], getattr(_internal, _name))

from ..ops.control_flow import foreach, while_loop, cond  # noqa: E402
contrib.foreach = foreach
contrib.while_loop = while_loop
contrib.cond = cond


def Custom(*args, **kwargs):
    """Run a Python CustomOp (reference: generated nd.Custom over
    src/operator/custom/custom.cc; see mxnet_tpu/operator.py)."""
    from ..operator import _custom_entry
    return _custom_entry(*args, **kwargs)


# creation helpers (reference: python/mxnet/ndarray/utils.py + ndarray.py) --
def zeros(shape, ctx=None, dtype=None, stype=None, **kwargs):
    if stype not in (None, "default"):
        from . import sparse as _sp
        return _sp.zeros(stype, shape, ctx=ctx, dtype=dtype)
    return _internal._zeros(shape=shape if isinstance(shape, (list, tuple)) else (shape,),
                            dtype=(dtype or "float32"), ctx=ctx or current_context())


def ones(shape, ctx=None, dtype=None, **kwargs):
    return _internal._ones(shape=shape if isinstance(shape, (list, tuple)) else (shape,),
                           dtype=(dtype or "float32"), ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype=None, **kwargs):
    return _internal._full(shape=shape if isinstance(shape, (list, tuple)) else (shape,),
                           value=float(val), dtype=(dtype or "float32"),
                           ctx=ctx or current_context())


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    return _internal._arange(start=start, stop=stop, step=step, repeat=repeat,
                             dtype=(dtype or "float32"), ctx=ctx or current_context())


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    return _internal._linspace(start=start, stop=stop, num=num, endpoint=endpoint,
                               dtype=(dtype or "float32"), ctx=ctx or current_context())


def eye(N, M=0, k=0, ctx=None, dtype=None):
    return _internal._eye(N=N, M=M, k=k, dtype=(dtype or "float32"),
                          ctx=ctx or current_context())


def zeros_like(data):
    return imperative_invoke("zeros_like", data)


def ones_like(data):
    return imperative_invoke("ones_like", data)


def waitall():
    """Block until all async computation completes (reference engine WaitForAll)."""
    import jax
    (_jnp.zeros(()) + 0).block_until_ready()
    try:
        jax.effects_barrier()
    except AttributeError:
        pass


def load(fname):
    """Load NDArrays saved by save() (reference: NDArray::Load, ndarray.cc)."""
    from ..serialization import load_ndarrays
    return load_ndarrays(fname)


def save(fname, data, format="mxtpu"):
    """Save list or dict of NDArrays (reference: NDArray::Save, ndarray.cc).
    format="mxnet" writes the reference dmlc-stream layout so stock MXNet
    ``mx.nd.load`` can read the file."""
    from ..serialization import save_ndarrays
    save_ndarrays(fname, data, format=format)


# random namespace ----------------------------------------------------------
random = types.ModuleType(__name__ + ".random")
sys.modules[random.__name__] = random


def _rand_fn(op_name, pub_name):
    def fn(*args, **kwargs):
        kwargs.setdefault("ctx", None)
        ctx = kwargs.pop("ctx", None)
        # positional params map (low/high etc.) — accept positionally
        op = _reg.get(op_name)
        if args and not isinstance(args[0], NDArray):
            # treat positionals as the op's leading scalar params
            pmap = _POSITIONAL.get(pub_name, ())
            for v, k in zip(args, pmap):
                kwargs.setdefault(k, v)
            args = ()
        out = invoke(op, args, kwargs)
        return out

    fn.__name__ = pub_name
    return fn


_POSITIONAL = {
    "uniform": ("low", "high", "shape"),
    "normal": ("loc", "scale", "shape"),
    "gamma": ("alpha", "beta", "shape"),
    "exponential": ("lam", "shape"),
    "poisson": ("lam", "shape"),
    "negative_binomial": ("k", "p", "shape"),
    "generalized_negative_binomial": ("mu", "alpha", "shape"),
    "randint": ("low", "high", "shape"),
    "multinomial": (),
}

for _pub, _opn in [
    ("uniform", "_random_uniform"), ("normal", "_random_normal"),
    ("gamma", "_random_gamma"), ("exponential", "_random_exponential"),
    ("poisson", "_random_poisson"),
    ("negative_binomial", "_random_negative_binomial"),
    ("generalized_negative_binomial", "_random_generalized_negative_binomial"),
    ("randint", "_random_randint"),
]:
    setattr(random, _pub, _rand_fn(_opn, _pub))

random.multinomial = _rand_fn("_sample_multinomial", "multinomial")
random.shuffle = _rand_fn("_shuffle", "shuffle")


def randn(*shape, ctx=None, dtype=None):
    return random.normal(0.0, 1.0, shape=shape, dtype=dtype or "float32")


random.randn = randn


def seed(s):
    from .. import _rng
    _rng.seed(s)


random.seed = seed
