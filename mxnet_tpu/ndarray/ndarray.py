"""NDArray: the imperative tensor, backed by an immutable jax.Array.

Reference: ``include/mxnet/ndarray.h:82`` / ``src/ndarray/ndarray.cc``.
The reference NDArray is a ref-counted chunk with *lazy async* semantics —
every op is pushed to the dependency engine and reads block at
``wait_to_read``.  On TPU, jax's async dispatch gives exactly those
semantics for free: ops return immediately with futures, ``.asnumpy()``
blocks.  Mutation (``a += b``, ``a[:] = x``, optimizer updates) is expressed
as handle rebinding: the Python ``NDArray`` object is a mutable handle whose
``_data`` is swapped for a new functional value — the analogue of the
reference's var-version chain (threaded_engine.h:115).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from .. import autograd
from .. import profiler as _prof
from ..base import np_dtype
from ..context import Context, current_context
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "empty", "concatenate", "invoke", "imperative_invoke"]


# stack of mutation trackers used by CachedOp tracing (gluon/block.py)
_MUTATION_TRACKERS = []
# eager monitor taps: fn(op_name, [NDArray outputs]) called per invoke
_MONITOR_TAPS = []


class NDArray:
    __slots__ = ("_data", "_ctx", "_entry", "_mark", "_grad", "_grad_req",
                 "__weakref__")

    def __init__(self, data, ctx=None):
        self._data = data
        self._ctx = ctx
        self._entry = None
        self._mark = False
        self._grad = None
        self._grad_req = "write"

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        s = 1
        for d in self._data.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def stype(self):
        return "default"

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        try:
            dev = next(iter(self._data.devices()))
            kind = "cpu" if dev.platform == "cpu" else "tpu"
            self._ctx = Context(kind, dev.id)
        except (AttributeError, TypeError):  # tracer
            return current_context()
        return self._ctx

    ctx = context

    def __len__(self):
        return self._data.shape[0]

    def __repr__(self):
        try:
            arr = _np.asarray(self._data)
            return "%s\n<NDArray %s @%s>" % (
                arr, "x".join(str(s) for s in self.shape), self.context)
        except Exception:
            return "<NDArray %s (traced)>" % (self._data,)

    # -- data access -------------------------------------------------------
    def asnumpy(self):
        """Blocking copy to host (reference: WaitForVar then copy)."""
        return _np.asarray(self._data)

    def asscalar(self):
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()

    def _set_data(self, new_data):
        """Rebind the handle to a new value (in-place mutation analogue)."""
        for tracker in _MUTATION_TRACKERS:
            tracker(self, new_data)
        self._data = new_data
        self._ctx = None

    def astype(self, dtype, copy=True):
        return invoke(_reg.get("Cast"), (self,), {"dtype": _np.dtype(dtype).name})

    def copy(self):
        return NDArray(self._data + 0 if False else jnp.asarray(self._data))

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self._data, other.context.jax_device())
                            if other._ctx is not None else self._data)
            return other
        if isinstance(other, Context):
            return self.as_in_context(other)
        raise TypeError(type(other))

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device()), ctx)

    as_in_ctx = as_in_context

    def detach(self):
        out = NDArray(self._data, self._ctx)
        return out

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate grad buffer and mark as leaf (reference ndarray.py:2167)."""
        self._entry = None
        self._mark = grad_req != "null"
        self._grad_req = grad_req
        self._grad = NDArray(jnp.zeros_like(self._data)) if self._mark else None

    @property
    def grad(self):
        return self._grad

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- shape ops ---------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape"):
            shape = tuple(kwargs["shape"])
        return invoke(_reg.get("Reshape"), (self,),
                      {"shape": shape, "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other):
        return invoke(_reg.get("reshape_like"), (self, other), {})

    def expand_dims(self, axis):
        return invoke(_reg.get("expand_dims"), (self,), {"axis": axis})

    def flatten(self):
        return invoke(_reg.get("Flatten"), (self,), {})

    def squeeze(self, axis=None):
        return invoke(_reg.get("squeeze"), (self,), {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke(_reg.get("transpose"), (self,), {"axes": axes or None})

    @property
    def T(self):
        return self.transpose()

    def swapaxes(self, dim1, dim2):
        return invoke(_reg.get("swapaxes"), (self,), {"dim1": dim1, "dim2": dim2})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke(_reg.get("SliceChannel"), (self,),
                      {"num_outputs": num_outputs, "axis": axis,
                       "squeeze_axis": squeeze_axis})

    def slice(self, begin, end, step=()):
        return invoke(_reg.get("slice"), (self,),
                      {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end):
        return invoke(_reg.get("slice_axis"), (self,),
                      {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke(_reg.get("take"), (self, indices), {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return invoke(_reg.get("one_hot"), (self,), dict(depth=depth, **kw))

    def tile(self, reps):
        return invoke(_reg.get("tile"), (self,), {"reps": reps})

    def broadcast_to(self, shape):
        return invoke(_reg.get("broadcast_to"), (self,), {"shape": shape})

    def broadcast_like(self, other):
        return invoke(_reg.get("broadcast_like"), (self, other), {})

    def pad(self, mode, pad_width, constant_value=0.0):
        return invoke(_reg.get("Pad"), (self,),
                      {"mode": mode, "pad_width": pad_width,
                       "constant_value": constant_value})

    # -- reductions / math methods (subset used pervasively) ---------------
    def _r(self, name, **kw):
        return invoke(_reg.get(name), (self,), kw)

    def sum(self, axis=None, keepdims=False):
        return self._r("sum", axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._r("mean", axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._r("prod", axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return self._r("max", axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._r("min", axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._r("norm", ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._r("argmax", axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._r("argmin", axis=axis, keepdims=keepdims)

    def abs(self):
        return self._r("abs")

    def sqrt(self):
        return self._r("sqrt")

    def square(self):
        return self._r("square")

    def exp(self):
        return self._r("exp")

    def log(self):
        return self._r("log")

    def clip(self, a_min, a_max):
        return self._r("clip", a_min=a_min, a_max=a_max)

    def sign(self):
        return self._r("sign")

    def round(self):
        return self._r("round")

    def sigmoid(self):
        return self._r("sigmoid")

    def relu(self):
        return self._r("relu")

    def tanh(self):
        return self._r("tanh")

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke(_reg.get("dot"), (self, other),
                      {"transpose_a": transpose_a, "transpose_b": transpose_b})

    # -- python operators --------------------------------------------------
    def _binop(self, name, sname, other, swap=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if swap else (self, other)
            return invoke(_reg.get(name), (a, b), {})
        return invoke(_reg.get(sname), (self,), {"scalar": float(other)})

    def __add__(self, o):
        return self._binop("broadcast_add", "_plus_scalar", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("broadcast_sub", "_minus_scalar", o)

    def __rsub__(self, o):
        if isinstance(o, NDArray):
            return o.__sub__(self)
        return invoke(_reg.get("_rminus_scalar"), (self,), {"scalar": float(o)})

    def __mul__(self, o):
        return self._binop("broadcast_mul", "_mul_scalar", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("broadcast_div", "_div_scalar", o)

    def __rtruediv__(self, o):
        if isinstance(o, NDArray):
            return o.__truediv__(self)
        return invoke(_reg.get("_rdiv_scalar"), (self,), {"scalar": float(o)})

    def __mod__(self, o):
        return self._binop("broadcast_mod", "_mod_scalar", o)

    def __rmod__(self, o):
        if isinstance(o, NDArray):
            return o.__mod__(self)
        return invoke(_reg.get("_rmod_scalar"), (self,), {"scalar": float(o)})

    def __pow__(self, o):
        return self._binop("broadcast_power", "_power_scalar", o)

    def __rpow__(self, o):
        return invoke(_reg.get("_rpower_scalar"), (self,), {"scalar": float(o)})

    def __neg__(self):
        return invoke(_reg.get("negative"), (self,), {})

    def __abs__(self):
        return invoke(_reg.get("abs"), (self,), {})

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop("broadcast_equal", "_equal_scalar", o)

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop("broadcast_not_equal", "_not_equal_scalar", o)

    def __gt__(self, o):
        return self._binop("broadcast_greater", "_greater_scalar", o)

    def __ge__(self, o):
        return self._binop("broadcast_greater_equal", "_greater_equal_scalar", o)

    def __lt__(self, o):
        return self._binop("broadcast_lesser", "_lesser_scalar", o)

    def __le__(self, o):
        return self._binop("broadcast_lesser_equal", "_lesser_equal_scalar", o)

    __hash__ = object.__hash__

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    # in-place forms rebind the handle (engine-write analogue)
    def __iadd__(self, o):
        self._set_data((self + o)._data)
        return self

    def __isub__(self, o):
        self._set_data((self - o)._data)
        return self

    def __imul__(self, o):
        self._set_data((self * o)._data)
        return self

    def __itruediv__(self, o):
        self._set_data((self / o)._data)
        return self

    # -- indexing ----------------------------------------------------------
    def _clean_index(self, key):
        if isinstance(key, NDArray):
            return key._data.astype(jnp.int32)
        if isinstance(key, tuple):
            return tuple(self._clean_index(k) for k in key)
        return key

    def __getitem__(self, key):
        key = self._clean_index(key)
        op = _reg.get("_getitem")
        return invoke(op, (self,), {"_key": key})

    def __setitem__(self, key, value):
        key = self._clean_index(key)
        if isinstance(value, NDArray):
            value = value._data
        if key is None or key == slice(None):
            new = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype), self.shape)
        else:
            new = self._data.at[key].set(jnp.asarray(value, dtype=self.dtype))
        self._set_data(new)

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]


@_reg.register("_getitem")
def _getitem_op(data, _key=None):
    """Basic/advanced indexing kernel behind NDArray.__getitem__ (reference:
    python/mxnet/ndarray/ndarray.py slicing)."""
    return data[_key]


# ---------------------------------------------------------------------------
# Central op dispatcher (reference: MXImperativeInvokeImpl, c_api_ndarray.cc:81
# → Imperative::Invoke, imperative.cc:87)
# ---------------------------------------------------------------------------
def invoke(op, args, kwargs, out=None):
    params = _reg.canonicalize_kwargs(kwargs)
    params.pop("name", None)
    out = params.pop("out", out)

    # assemble ordered tensor inputs; scalar positional args (ints, floats,
    # strings, tuples — e.g. nd.swapaxes(x, 0, 1)) map onto fn's parameter
    # names by position, matching the reference's generated signatures
    inputs = []
    if op.arg_names != ["args"]:
        for i, a in enumerate(args):
            if isinstance(a, (NDArray, jnp.ndarray, _np.ndarray)) or a is None:
                inputs.append(a)
            elif i < len(op.fn_params):
                params.setdefault(op.fn_params[i], a)
            else:
                inputs.append(a)
    else:
        inputs = [a for a in args]
    if op.arg_names != ["args"]:
        names = list(op.arg_names)
        for idx, aux_name in sorted(op.aux.items()):
            names.append(aux_name)
        for name in names[len(inputs):]:
            if name in params and isinstance(params[name], (NDArray, jnp.ndarray, _np.ndarray)):
                inputs.append(params.pop(name))
            elif name in params and params[name] is None:
                params.pop(name)
    # convert
    nd_inputs = []
    for a in inputs:
        if isinstance(a, NDArray):
            nd_inputs.append(a)
        elif a is None:
            continue
        else:
            nd_inputs.append(NDArray(jnp.asarray(a)))

    raw = [a._data for a in nd_inputs]
    if op.needs_train:
        params = dict(params)
        params["_train"] = autograd.is_training()

    n_aux = len(op.aux)
    n_diff = len(raw) - n_aux if n_aux else len(raw)

    tracked = (
        autograd.is_recording() and op.differentiable
        and any(a._entry is not None or a._mark for a in nd_inputs[:n_diff])
    )

    _prof_on = _prof._PROFILING
    _t0 = _prof._now_us() if _prof_on else 0

    if tracked:
        aux_raw = raw[n_diff:]

        def fwd(*xs):
            return op.fn(*(list(xs) + aux_raw), **params)

        outs, vjp_fn = jax.vjp(fwd, *raw[:n_diff])
        fwd_multi = isinstance(outs, tuple)
        if not fwd_multi:
            vjp_fn = (lambda _v: lambda cts: _v(cts[0]))(vjp_fn)
    else:
        outs = op.fn(*raw, **params)
        vjp_fn = None

    outs_tuple = outs if isinstance(outs, tuple) else (outs,)

    if _prof_on:
        # dispatch-side op event (device timeline comes from jax.profiler)
        _prof.record_event(op.name, "operator", _t0, _prof._now_us() - _t0)

    # aux-state mutation under training (reference: FMutateInputs)
    if op.aux_update is not None and params.get("_train") and not params.get("use_global_stats"):
        updates = op.aux_update(raw, outs_tuple, params)
        for idx, val in updates.items():
            nd_inputs[idx]._set_data(val)

    # unconditional input mutation (reference: FMutateInputs on the
    # optimizer-update ops — sgd_mom_update writes mom in place)
    for in_idx, out_idx in op.mutates.items():
        nd_inputs[in_idx]._set_data(outs_tuple[out_idx])

    # eager per-op monitor taps (MXExecutorSetMonitorCallback analogue)
    if _MONITOR_TAPS:
        _tap_outs = [NDArray(o) for o in outs_tuple[:op.n_outputs(params)]]
        for tap in _MONITOR_TAPS:
            tap(op.name, _tap_outs)

    n_public = op.n_outputs(params)
    out_nds = [NDArray(o) for o in outs_tuple[:n_public]]

    if tracked:
        node = autograd.record_op(vjp_fn, nd_inputs[:n_diff], list(outs_tuple),
                                  fwd, list(raw[:n_diff]), fwd_multi)
        for i, o in enumerate(out_nds):
            o._entry = (node, i)

    if out is not None:
        if isinstance(out, (list, tuple)):
            for o_dst, o_src in zip(out, out_nds):
                o_dst._set_data(o_src._data)
                o_dst._entry = o_src._entry
            return out if len(out) > 1 else out[0]
        out._set_data(out_nds[0]._data)
        out._entry = out_nds[0]._entry
        return out
    if len(out_nds) == 1:
        return out_nds[0]
    return out_nds


def imperative_invoke(op_name, *args, **kwargs):
    """Invoke a registered op by name (the C API MXImperativeInvoke analogue)."""
    return invoke(_reg.get(op_name), args, kwargs)


# ---------------------------------------------------------------------------
# creation helpers
# ---------------------------------------------------------------------------
def array(source_array, ctx=None, dtype=None):
    """Create an NDArray.  dtype defaults to source.dtype for NDArray sources
    and float32 otherwise, matching the reference (ndarray/ndarray.py array)."""
    if isinstance(source_array, NDArray):
        data = source_array._data
        if dtype is not None:
            data = data.astype(np_dtype(dtype))
    else:
        np_arr = _np.asarray(source_array)
        data = np_arr.astype(np_dtype(dtype) if dtype is not None else _np.float32)
    ctx = ctx or current_context()
    return NDArray(jax.device_put(jnp.asarray(data), ctx.jax_device()), ctx)


def empty(shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    return NDArray(jax.device_put(
        jnp.zeros(shape, dtype=np_dtype(dtype or "float32")), ctx.jax_device()), ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return invoke(_reg.get("Concat"), tuple(arrays), {"dim": axis})
