"""Sparse NDArray storage types: row_sparse and csr.

Reference: ``include/mxnet/ndarray.h:61-66`` (storage types),
``python/mxnet/ndarray/sparse.py``.  On TPU there is no cuSPARSE analogue;
row_sparse is (indices, values) pairs — the natural output of embedding
gradients — and csr is (indptr, indices, data).  Dense fallback is via
``todense``; ops keep sparsity only where it pays (sparse dot, retain,
optimizer row updates).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import np_dtype
from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "cast_storage", "retain", "dot"]


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: values for a subset of rows (indices sorted ascending)."""

    __slots__ = ("data", "indices", "_shape")

    def __init__(self, data, indices, shape):
        super().__init__(None)
        self.data = data            # NDArray (nnz_rows, *row_shape)
        self.indices = indices      # NDArray (nnz_rows,) int64
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def __repr__(self):
        return "<RowSparseNDArray %s @%s>" % ("x".join(map(str, self._shape)),
                                              self.context)

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        out = jnp.zeros(self._shape, dtype=self.data._data.dtype)
        # .add (not .set) so rows with duplicate indices accumulate — the
        # invariant a compiler-friendly sparse sum relies on
        out = out.at[self.indices._data.astype(jnp.int32)].add(self.data._data)
        return NDArray(out)

    tostype = NDArray.tostype

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data.copy()
            other.indices = self.indices.copy()
            other._shape = self._shape
            return other
        return self.todense().copyto(other)

    def wait_to_read(self):
        self.data.wait_to_read()

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return add_rsp(self, other)
        return self.todense() + other


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("data", "indices", "indptr", "_shape")

    def __init__(self, data, indices, indptr, shape):
        super().__init__(None)
        self.data = data
        self.indices = indices
        self.indptr = indptr
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def __repr__(self):
        return "<CSRNDArray %s @%s>" % ("x".join(map(str, self._shape)),
                                        self.context)

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        m, n = self._shape
        indptr = self.indptr._data.astype(jnp.int32)
        cols = self.indices._data.astype(jnp.int32)
        vals = self.data._data
        # row id per nnz via searchsorted on indptr
        nnz = vals.shape[0]
        rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        out = jnp.zeros((m, n), dtype=vals.dtype)
        return NDArray(out.at[rows, cols].add(vals))

    def wait_to_read(self):
        self.data.wait_to_read()


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else _dense_array(data, ctx, dtype)
        indices = indices if isinstance(indices, NDArray) else _dense_array(
            indices, ctx, "int64")
        if shape is None:
            raise ValueError("shape required")
        return RowSparseNDArray(data, indices, shape)
    # dense source
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    nz = _np.where(_np.abs(dense).reshape(dense.shape[0], -1).sum(-1) > 0)[0]
    return RowSparseNDArray(
        _dense_array(dense[nz], ctx, dtype or dense.dtype),
        _dense_array(nz, ctx, "int64"), dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data if isinstance(data, NDArray) else _dense_array(data, ctx, dtype)
        indices = indices if isinstance(indices, NDArray) else _dense_array(
            indices, ctx, "int64")
        indptr = indptr if isinstance(indptr, NDArray) else _dense_array(
            indptr, ctx, "int64")
        return CSRNDArray(data, indices, indptr, shape)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    m, n = dense.shape
    # vectorized construction (np.nonzero yields row-major = CSR order)
    rows, cols = _np.nonzero(dense)
    vals = dense[rows, cols]
    indptr = _np.zeros(m + 1, _np.int64)
    _np.cumsum(_np.bincount(rows, minlength=m), out=indptr[1:])
    return CSRNDArray(
        _dense_array(_np.asarray(vals, dtype=dense.dtype), ctx, dtype or dense.dtype),
        _dense_array(cols, ctx, "int64"), _dense_array(indptr, ctx, "int64"),
        dense.shape)


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = np_dtype(dtype or "float32")
    if stype == "row_sparse":
        row_shape = tuple(shape[1:])
        return RowSparseNDArray(
            _dense_array(_np.zeros((0,) + row_shape, dtype), ctx),
            _dense_array(_np.zeros((0,), _np.int64), ctx, "int64"), shape)
    if stype == "csr":
        return CSRNDArray(
            _dense_array(_np.zeros((0,), dtype), ctx),
            _dense_array(_np.zeros((0,), _np.int64), ctx, "int64"),
            _dense_array(_np.zeros((shape[0] + 1,), _np.int64), ctx, "int64"),
            shape)
    if stype == "default":
        from . import zeros as dzeros
        return dzeros(shape, ctx=ctx, dtype=dtype)
    raise ValueError(stype)


def cast_storage(arr, stype):
    """Storage-type conversion (reference: src/operator/tensor/cast_storage.cc
    CastStorageDnsRspImpl / CastStorageDnsCsrImpl).

    Runs device-side: the nonzero scan stays on the accelerator; only the
    data-dependent nnz forces a sync (exactly where the reference's CPU
    sizing pass sits).
    """
    if stype == arr.stype:
        return arr
    if stype == "default":
        return arr.todense()
    if isinstance(arr, (RowSparseNDArray, CSRNDArray)):
        arr = arr.todense()
    d = arr._data
    if stype == "row_sparse":
        row_nz = jnp.any(d.reshape(d.shape[0], -1) != 0, axis=-1)
        (nz,) = jnp.nonzero(row_nz)  # sync: dynamic nnz
        return RowSparseNDArray(NDArray(d[nz]),
                                NDArray(nz.astype(jnp.int64)), d.shape)
    if stype == "csr":
        rows, cols = jnp.nonzero(d)  # sync: dynamic nnz
        vals = d[rows, cols]
        counts = jnp.bincount(rows, length=d.shape[0])
        indptr = jnp.concatenate([jnp.zeros((1,), jnp.int64),
                                  jnp.cumsum(counts).astype(jnp.int64)])
        return CSRNDArray(NDArray(vals), NDArray(cols.astype(jnp.int64)),
                          NDArray(indptr), d.shape)
    raise ValueError(stype)


def retain(rsp, indices):
    """sparse_retain: keep only the requested rows (reference:
    src/operator/tensor/sparse_retain.cc).

    Device-side and static-shape: the output has exactly ``len(indices)``
    rows — requested rows missing from the source come out as zero rows,
    matching the reference's RspImpl (it allocates idx-sized output and
    copies only the hits).  No host round-trip, so async dispatch holds.
    """
    idx_keep = indices._data.astype(jnp.int64) if isinstance(indices, NDArray) \
        else jnp.asarray(indices, jnp.int64)
    src_idx = rsp.indices._data.astype(jnp.int64)
    src_data = rsp.data._data
    nnz = src_idx.shape[0]
    if nnz == 0:
        zero_rows = jnp.zeros((idx_keep.shape[0],) + tuple(rsp.data.shape[1:]),
                              src_data.dtype)
        return RowSparseNDArray(NDArray(zero_rows), NDArray(idx_keep),
                                rsp.shape)
    pos = jnp.searchsorted(src_idx, idx_keep)
    pos_c = jnp.clip(pos, 0, nnz - 1)
    hit = (pos < nnz) & (src_idx[pos_c] == idx_keep)
    bshape = (-1,) + (1,) * (src_data.ndim - 1)
    data = jnp.where(hit.reshape(bshape), src_data[pos_c], 0)
    return RowSparseNDArray(NDArray(data), NDArray(idx_keep), rsp.shape)


def add_rsp(a, b):
    """Row-sparse + row-sparse with exact index-union semantics.

    All heavy work (sort, segment-sum) runs on device; the only host sync is
    the scalar unique-row count (the output nnz is inherently data-dependent,
    as in the reference's RspRspOp which sizes the output on CPU too).
    """
    idx = jnp.concatenate([a.indices._data.astype(jnp.int64),
                           b.indices._data.astype(jnp.int64)])
    if idx.shape[0] == 0:
        return RowSparseNDArray(a.data.copy(), a.indices.copy(), a.shape)
    data = jnp.concatenate([a.data._data, b.data._data], axis=0)
    order = jnp.argsort(idx)
    idx_s = idx[order]
    data_s = data[order]
    is_new = jnp.concatenate([jnp.ones((1,), jnp.int32),
                              (idx_s[1:] != idx_s[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(is_new) - 1
    n_unique = int(seg[-1]) + 1  # scalar sync: dynamic output nnz
    out = jax.ops.segment_sum(data_s, seg, num_segments=n_unique)
    out_idx = jnp.zeros((n_unique,), jnp.int64).at[seg].set(idx_s)
    return RowSparseNDArray(NDArray(out), NDArray(out_idx), a.shape)


def _csr_rows(indptr, nnz):
    """Row id per nnz element from indptr (device-side)."""
    return jnp.searchsorted(indptr, jnp.arange(nnz, dtype=indptr.dtype),
                            side="right") - 1


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot without densifying (reference:
    src/operator/tensor/dot-inl.h DotCsrDnsDnsImpl / DotCsrDnsRspImpl).

    csr × dense lowers to a gather + segment-sum — the TPU-native form of
    the reference's per-row CSR kernels; the contraction stays O(nnz·k).
    """
    if isinstance(lhs, CSRNDArray):
        if transpose_b:
            raise NotImplementedError("transpose_b unsupported for sparse dot")
        rhs_d = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        m, n = lhs.shape
        vals = lhs.data._data
        cols = lhs.indices._data.astype(jnp.int32)
        nnz = vals.shape[0]
        if nnz == 0:
            out_rows = n if transpose_a else m
            return NDArray(jnp.zeros((out_rows,) + tuple(rhs_d.shape[1:]),
                                     rhs_d.dtype))
        indptr = lhs.indptr._data.astype(jnp.int32)
        rows = _csr_rows(indptr, nnz)
        if transpose_a:
            # out[c, :] = sum_k vals[k] * rhs[rows[k], :] for cols[k] == c
            contrib = vals[:, None] * rhs_d[rows]
            out = jax.ops.segment_sum(contrib, cols, num_segments=n)
        else:
            # out[r, :] = sum_k vals[k] * rhs[cols[k], :] for rows[k] == r
            contrib = vals[:, None] * rhs_d[cols]
            out = jax.ops.segment_sum(contrib, rows, num_segments=m)
        return NDArray(out)
    raise TypeError("sparse dot expects CSR lhs")
