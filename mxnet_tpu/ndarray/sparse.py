"""Sparse NDArray storage types: row_sparse and csr.

Reference: ``include/mxnet/ndarray.h:61-66`` (storage types),
``python/mxnet/ndarray/sparse.py``.  On TPU there is no cuSPARSE analogue;
row_sparse is (indices, values) pairs — the natural output of embedding
gradients — and csr is (indptr, indices, data).  Dense fallback is via
``todense``; ops keep sparsity only where it pays (sparse dot, retain,
optimizer row updates).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import np_dtype
from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "cast_storage", "retain", "dot"]


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: values for a subset of rows (indices sorted ascending)."""

    __slots__ = ("data", "indices", "_shape")

    def __init__(self, data, indices, shape):
        super().__init__(None)
        self.data = data            # NDArray (nnz_rows, *row_shape)
        self.indices = indices      # NDArray (nnz_rows,) int64
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def __repr__(self):
        return "<RowSparseNDArray %s @%s>" % ("x".join(map(str, self._shape)),
                                              self.context)

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        out = jnp.zeros(self._shape, dtype=self.data._data.dtype)
        out = out.at[self.indices._data.astype(jnp.int32)].set(self.data._data)
        return NDArray(out)

    tostype = NDArray.tostype

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data.copy()
            other.indices = self.indices.copy()
            other._shape = self._shape
            return other
        return self.todense().copyto(other)

    def wait_to_read(self):
        self.data.wait_to_read()

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return add_rsp(self, other)
        return self.todense() + other


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("data", "indices", "indptr", "_shape")

    def __init__(self, data, indices, indptr, shape):
        super().__init__(None)
        self.data = data
        self.indices = indices
        self.indptr = indptr
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    def __repr__(self):
        return "<CSRNDArray %s @%s>" % ("x".join(map(str, self._shape)),
                                        self.context)

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        m, n = self._shape
        indptr = self.indptr._data.astype(jnp.int32)
        cols = self.indices._data.astype(jnp.int32)
        vals = self.data._data
        # row id per nnz via searchsorted on indptr
        nnz = vals.shape[0]
        rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        out = jnp.zeros((m, n), dtype=vals.dtype)
        return NDArray(out.at[rows, cols].add(vals))

    def wait_to_read(self):
        self.data.wait_to_read()


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else _dense_array(data, ctx, dtype)
        indices = indices if isinstance(indices, NDArray) else _dense_array(
            indices, ctx, "int64")
        if shape is None:
            raise ValueError("shape required")
        return RowSparseNDArray(data, indices, shape)
    # dense source
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    nz = _np.where(_np.abs(dense).reshape(dense.shape[0], -1).sum(-1) > 0)[0]
    return RowSparseNDArray(
        _dense_array(dense[nz], ctx, dtype or dense.dtype),
        _dense_array(nz, ctx, "int64"), dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data if isinstance(data, NDArray) else _dense_array(data, ctx, dtype)
        indices = indices if isinstance(indices, NDArray) else _dense_array(
            indices, ctx, "int64")
        indptr = indptr if isinstance(indptr, NDArray) else _dense_array(
            indptr, ctx, "int64")
        return CSRNDArray(data, indices, indptr, shape)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    m, n = dense.shape
    indptr = [0]
    cols = []
    vals = []
    for i in range(m):
        nz = _np.where(dense[i] != 0)[0]
        cols.extend(nz.tolist())
        vals.extend(dense[i][nz].tolist())
        indptr.append(len(cols))
    return CSRNDArray(
        _dense_array(_np.asarray(vals, dtype=dense.dtype), ctx, dtype or dense.dtype),
        _dense_array(cols, ctx, "int64"), _dense_array(indptr, ctx, "int64"),
        dense.shape)


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = np_dtype(dtype or "float32")
    if stype == "row_sparse":
        row_shape = tuple(shape[1:])
        return RowSparseNDArray(
            _dense_array(_np.zeros((0,) + row_shape, dtype), ctx),
            _dense_array(_np.zeros((0,), _np.int64), ctx, "int64"), shape)
    if stype == "csr":
        return CSRNDArray(
            _dense_array(_np.zeros((0,), dtype), ctx),
            _dense_array(_np.zeros((0,), _np.int64), ctx, "int64"),
            _dense_array(_np.zeros((shape[0] + 1,), _np.int64), ctx, "int64"),
            shape)
    if stype == "default":
        from . import zeros as dzeros
        return dzeros(shape, ctx=ctx, dtype=dtype)
    raise ValueError(stype)


def cast_storage(arr, stype):
    """Reference: src/operator/tensor/cast_storage.cc."""
    if stype == arr.stype:
        return arr
    if stype == "default":
        return arr.todense()
    if stype == "row_sparse":
        dense = arr.asnumpy() if not isinstance(arr, NDArray) else arr.asnumpy()
        return row_sparse_array(dense)
    if stype == "csr":
        return csr_matrix(arr.asnumpy())
    raise ValueError(stype)


def retain(rsp, indices):
    """sparse_retain: keep only given rows (reference: sparse_retain.cc)."""
    idx_keep = indices._data.astype(jnp.int64) if isinstance(indices, NDArray) \
        else jnp.asarray(indices, jnp.int64)
    cur = rsp.indices._data
    mask = jnp.isin(cur, idx_keep)
    keep_pos = _np.where(_np.asarray(mask))[0]
    return RowSparseNDArray(
        NDArray(rsp.data._data[keep_pos]),
        NDArray(cur[keep_pos]), rsp.shape)


def add_rsp(a, b):
    idx = _np.union1d(_np.asarray(a.indices._data), _np.asarray(b.indices._data))
    n = len(idx)
    row_shape = a.data.shape[1:]
    out = jnp.zeros((n,) + tuple(row_shape), a.data._data.dtype)
    pos_a = _np.searchsorted(idx, _np.asarray(a.indices._data))
    pos_b = _np.searchsorted(idx, _np.asarray(b.indices._data))
    out = out.at[pos_a].add(a.data._data)
    out = out.at[pos_b].add(b.data._data)
    return RowSparseNDArray(NDArray(out), NDArray(jnp.asarray(idx, jnp.int64)),
                            a.shape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (reference: src/operator/tensor/dot.cc sparse paths)."""
    if isinstance(lhs, CSRNDArray):
        dense = lhs.todense()
        from .ndarray import invoke
        from ..ops import registry as _reg
        return invoke(_reg.get("dot"), (dense, rhs),
                      {"transpose_a": transpose_a, "transpose_b": transpose_b})
    raise TypeError("sparse dot expects CSR lhs")
