"""Code-generates the ``nd.*`` function namespace from the op registry.

Reference: ``python/mxnet/ndarray/register.py:156`` — at import time the
reference lists ops through the C API and synthesizes Python wrappers that
marshal string kwargs into MXImperativeInvoke.  Here the wrapper closes over
the registered Op and calls the in-process dispatcher directly.
"""
from __future__ import annotations

import jax

from ..context import current_context
from ..ops import registry as _reg
from .ndarray import NDArray, invoke


def _make_op_func(op, name):
    def fn(*args, **kwargs):
        ctx = kwargs.pop("ctx", None)
        out = invoke(op, args, kwargs)
        if ctx is not None and isinstance(out, NDArray):
            dev = ctx.jax_device()
            out = NDArray(jax.device_put(out._data, dev), ctx)
        return out

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = op.doc or ("%s operator (TPU-native)." % name)
    return fn


def populate(target_module, internal_module=None):
    """Install a function per registered op; _-prefixed go to _internal."""
    for name in _reg.list_ops():
        op = _reg.get(name)
        f = _make_op_func(op, name)
        if name.startswith("_"):
            if internal_module is not None:
                setattr(internal_module, name, f)
        else:
            if not hasattr(target_module, name):
                setattr(target_module, name, f)
        if internal_module is not None and not hasattr(internal_module, name):
            setattr(internal_module, name, f)
