"""Testing oracle utilities.

Reference: ``python/mxnet/test_utils.py`` — the numeric oracle is NumPy plus
finite differences (assert_almost_equal:470, check_numeric_gradient:792,
check_symbolic_forward:925, check_consistency:1207).  Here the gradient
oracle is both finite differences *and* jax.grad on a NumPy-equivalent
function; check_consistency compares TPU vs CPU-jax executions.
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from . import autograd
from .ndarray import NDArray


def default_context():
    from .context import current_context
    return current_context()


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b.astype(a.dtype) if a.dtype != b.dtype else b,
                               rtol=rtol, atol=atol,
                               err_msg="%s vs %s mismatch" % names)


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    try:
        assert_almost_equal(a, b, rtol, atol)
        return True
    except AssertionError:
        return False


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    arr = np.random.uniform(-1, 1, size=shape).astype(dtype or np.float32)
    out = nd.array(arr, ctx=ctx)
    if stype != "default":
        return out.tostype(stype)
    return out


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4):
    """Compare autograd gradients with central finite differences.

    `fn`: callable taking NDArrays, returning a scalar-reducible NDArray.
    `inputs`: list of numpy arrays (float64 recommended for the FD oracle).
    Reference: test_utils.py:792 check_numeric_gradient.
    """
    nds = [nd.array(x.astype(np.float32)) for x in inputs]
    for a in nds:
        a.attach_grad()
    with autograd.record():
        out = fn(*nds)
        loss = out.sum() if out.size > 1 else out
    loss.backward()
    analytic = [a.grad.asnumpy() for a in nds]

    for i, x in enumerate(inputs):
        numeric = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = _eval_sum(fn, inputs)
            flat[j] = orig - eps
            fm = _eval_sum(fn, inputs)
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(
            analytic[i], numeric.astype(analytic[i].dtype), rtol=rtol, atol=atol,
            err_msg="gradient mismatch for input %d" % i)


def _eval_sum(fn, np_inputs):
    nds = [nd.array(x.astype(np.float32)) for x in np_inputs]
    out = fn(*nds)
    return float(out.sum().asscalar() if out.size > 1 else out.asscalar())


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5,
                      require_distinct=False):
    """Run `fn` under each context and compare outputs pairwise
    (reference: test_utils.py:1207 — gpu/cpu/fp16 consistency).

    With ``require_distinct=True`` the default ctx_list becomes
    [tpu(0), cpu(0)] — the reference's gpu-vs-cpu pattern mapped to
    TPU-vs-host-XLA — and the call fails loudly if the legs land on one
    platform anyway (VERDICT r4 weak item 5: a single-platform host made
    the check silently vacuous).  Default-args callers keep the old
    single-leg behavior and tolerances; cross-platform runs should pass
    tolerances matching the TPU's bf16-ish matmul precision (~2e-2)."""
    from .context import cpu, tpu
    if ctx_list is None:
        ctx_list = [tpu(0), cpu(0)] if require_distinct else [cpu(0)]
    results = []
    platforms = []
    for ctx in ctx_list:
        with ctx:
            nds = [nd.array(x, ctx=ctx) for x in inputs]
            try:
                platforms.append(
                    next(iter(nds[0]._data.devices())).platform)
            except Exception:
                platforms.append(None)
            results.append(fn(*nds).asnumpy())
    if require_distinct:
        if None in platforms:
            # a leg whose platform cannot be determined must not count
            # as "distinct" — that would quietly re-open the vacuity hole
            raise RuntimeError(
                "check_consistency could not determine the platform of "
                "every leg (got %r); cannot certify distinctness"
                % (platforms,))
        if len(set(platforms)) < 2:
            raise RuntimeError(
                "check_consistency is degenerate: all %d legs ran on "
                "platform %r — a cross-platform consistency claim needs "
                "two distinct backends (ctx_list=%r)"
                % (len(platforms), platforms[0] if platforms else None,
                   ctx_list))
    for r in results[1:]:
        np.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)
    return results


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def list_gpus():
    """Indices of this process's accelerator devices (reference:
    test_utils.py list_gpus) — local, so `[mx.gpu(i) for i in list_gpus()]`
    maps one context per addressable chip."""
    from .context import _accelerator_devices
    try:
        return list(range(len([d for d in _accelerator_devices()
                               if d.platform != "cpu"])))
    except Exception:
        return []


def download(url, fname=None, dirname=None, overwrite=False):
    """Reference: test_utils.py download.  This environment has no network
    egress; the function exists for API parity and raises with guidance."""
    raise RuntimeError(
        "no network egress in this environment — place %r locally and pass "
        "the path instead" % url)


def separable_images(rng, n, nclass=4, size=12, channels=3, noise=0.4,
                     base=1.2):
    """Class-separable synthetic images: class c lights quadrant
    ((c//2)%%2, c%%2) with brightness base + 0.2*(c//4) over gaussian
    noise.  NHWC float32; labels float32.  Used by the convergence suite
    (tests/test_train.py) and the bench accuracy gate in place of real
    image datasets (zero-egress environment)."""
    import numpy as _np
    y = (_np.arange(n) % nclass).astype(_np.float32)
    X = rng.randn(n, size, size, channels).astype(_np.float32) * noise
    q = size // 2
    for i in range(n):
        c = int(y[i])
        r0, c0 = (c // 2) % 2 * q, c % 2 * q
        X[i, r0:r0 + q, c0:c0 + q] += base + 0.2 * (c // 4)
    return X, y
