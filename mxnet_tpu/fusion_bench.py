"""Host-only fusion-tier bench (the r05 subprocess pattern).

Run as ``python -m mxnet_tpu.fusion_bench`` under ``JAX_PLATFORMS=cpu``
(bench.py's ``fusion`` stage does, BEFORE backend acquisition, so the
keys stay live when the TPU is down).  Emits one JSON line:

- ``fused_optimizer_speedup_host``: REAL measured wall-time ratio of
  the unfused per-parameter optimizer update (what ``_apply_groups``
  traces without fusion: one eqn chain per parameter) vs the shipped
  fused flat kernel (``ops/fused_optimizer.py``, Pallas interpret on
  the host — one pass, one dispatch).  Gated ``higher`` ≥1.2× in
  tools/bench_compare.py from r06.
- ``modeled_fusion_bytes_saved_pct``: the deterministic modeled win of
  the optimizer chain from the ``fused_optimizer_update`` budget
  builder (the fusion pass's bytes-saved over the unfused chain).
- ``fusion_numerics_ok``: 1.0 iff fused SGD+momentum AND Adam match
  the unfused ``Optimizer.update`` spelling within FLOAT_TOL and the
  fused path is bitwise-deterministic across two runs — gated at zero
  slack.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

FLOAT_TOL = 1e-5      # fused vs unfused update, after BENCH_STEPS steps
BENCH_REPS = 40       # timing samples per arm (median)
NPAR, PSIZE = 96, 4096   # 96 parameters x 4096 f32 — the many-small-
#                          params regime where unfused dispatch hurts


def _bench(fn, args, reps=BENCH_REPS):
    import jax
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.analysis.budget_models import (
        fused_update_fusion_numbers)
    from mxnet_tpu.ops import fused_optimizer as fo
    from mxnet_tpu.parallel.functional import functional_optimizer_update

    out = {}

    # modeled (deterministic, device-free): the budget builder's numbers
    numbers = fused_update_fusion_numbers()
    out["modeled_fusion_bytes_saved_pct"] = numbers[
        "modeled_fusion_bytes_saved_pct"]
    out["modeled_adam_bytes_saved_pct"] = numbers["adam"]["saved_pct"]

    # measured: unfused per-param chain vs the fused flat kernel
    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4)
    rng = np.random.RandomState(7)
    ws = [jnp.asarray(rng.randn(PSIZE).astype("f")) for _ in range(NPAR)]
    gs = [jnp.asarray(rng.randn(PSIZE).astype("f")) for _ in range(NPAR)]
    ms = [jnp.asarray(rng.randn(PSIZE).astype("f")) for _ in range(NPAR)]
    wf = jnp.concatenate(ws)
    gf = jnp.concatenate(gs)
    mf = jnp.concatenate(ms)
    lr = jnp.float32(0.1)

    @jax.jit
    def unfused(ws, gs, ms, lr):
        outs = [functional_optimizer_update(opt, 0, w, g, m, lr, 1)
                for w, g, m in zip(ws, gs, ms)]
        return [o[0] for o in outs], [o[1] for o in outs]

    @jax.jit
    def fused(wf, gf, mf, lr):
        return fo.fused_sgd_momentum(wf, gf, mf, lr, momentum=0.9,
                                     wd=1e-4, interpret=True)

    nw_u, nm_u = unfused(ws, gs, ms, lr)          # warm (compile)
    jax.block_until_ready((nw_u, nm_u))
    nw_f, nm_f = fused(wf, gf, mf, lr)
    jax.block_until_ready((nw_f, nm_f))

    t_unfused = _bench(unfused, (ws, gs, ms, lr))
    t_fused = _bench(fused, (wf, gf, mf, lr))
    out["fused_optimizer_unfused_ms"] = round(t_unfused * 1e3, 4)
    out["fused_optimizer_fused_ms"] = round(t_fused * 1e3, 4)
    out["fused_optimizer_speedup_host"] = round(t_unfused / t_fused, 3)

    # numerics: fused == unfused within FLOAT_TOL (sgd-momentum above,
    # adam below), and the fused path bitwise-repeats
    err = max(float(jnp.max(jnp.abs(jnp.concatenate(nw_u) - nw_f))),
              float(jnp.max(jnp.abs(jnp.concatenate(nm_u) - nm_f))))
    nw_f2, nm_f2 = fused(wf, gf, mf, lr)
    bitwise = bool((np.asarray(nw_f) == np.asarray(nw_f2)).all()
                   and (np.asarray(nm_f) == np.asarray(nm_f2)).all())

    adam = opt_mod.Adam(learning_rate=0.01, wd=1e-4)
    vf = jnp.asarray(np.abs(rng.randn(NPAR * PSIZE)).astype("f"))
    t = jnp.int32(3)
    aw_u, astate_u = functional_optimizer_update(
        adam, 0, wf, gf, (mf, vf), jnp.float32(0.01), t)
    aw_f, astate_f = fo.fused_optimizer_update(
        adam, 0, wf, gf, (mf, vf), jnp.float32(0.01), t, interpret=True)
    err = max(err, float(jnp.max(jnp.abs(aw_u - aw_f))),
              float(jnp.max(jnp.abs(astate_u[0] - astate_f[0]))),
              float(jnp.max(jnp.abs(astate_u[1] - astate_f[1]))))
    out["fusion_numerics_max_err"] = float(err)
    out["fusion_numerics_ok"] = 1.0 if (err <= FLOAT_TOL
                                        and bitwise) else 0.0

    print(json.dumps(out))
    return 0 if out["fusion_numerics_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
