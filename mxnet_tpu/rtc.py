"""Runtime kernel compilation: the `mx.rtc` capability, TPU-native.

Reference: ``include/mxnet/rtc.h:39`` CudaModule / ``python/mxnet/rtc.py``
— users compile CUDA C source strings at runtime (NVRTC) and launch them on
NDArrays.  The TPU analogue is **Pallas**: users write a Python kernel
function (Pallas or plain jax), and ``PallasModule``/``register_op`` wires
it into the op registry so it is callable as ``mx.nd.<name>`` / composable
into symbols — the same "user-supplied kernel as a first-class op"
capability, with Mosaic replacing NVRTC.
"""
from __future__ import annotations

from .ndarray import NDArray, invoke
from .ops import registry as _reg

__all__ = ["PallasModule", "register_op", "CudaModule"]


def register_op(name, fn=None, **reg_kwargs):
    """Register a user jax/Pallas function as an operator
    (usable as decorator).  The function must be pure: arrays in → arrays
    out; it becomes jittable, differentiable (via jax autodiff or its own
    custom_vjp) and symbol-composable like built-in ops."""
    if fn is None:
        return lambda f: register_op(name, f, **reg_kwargs)
    _reg.register(name, **reg_kwargs)(fn)
    # expose on the nd / sym namespaces like import-time codegen does
    from . import ndarray as nd_mod
    from .ndarray import register as nd_register
    nd_register.populate(nd_mod, getattr(nd_mod, "_internal", None))
    import sys
    sym_mod = sys.modules.get("mxnet_tpu.symbol")
    if sym_mod is not None:
        op = _reg.get(name)
        setattr(sym_mod, name, sym_mod._make_sym_func(op, name))
    return fn


class PallasModule:
    """User kernel container (reference: rtc.CudaModule).

    `kernels` is a dict of name → pure jax/Pallas callables (replacing the
    reference's CUDA source text).  ``get_kernel(name)`` returns a
    launchable wrapper whose ``launch(args)`` runs on device.
    """

    def __init__(self, kernels, exports=()):
        if callable(kernels):
            kernels = {getattr(kernels, "__name__", "kernel"): kernels}
        self._kernels = dict(kernels)
        self.exports = tuple(exports) or tuple(self._kernels)

    def get_kernel(self, name, signature=None):
        fn = self._kernels[name]
        return _Kernel(name, fn)


class _Kernel:
    def __init__(self, name, fn):
        self.name = name
        self._fn = fn
        import jax
        self._jitted = jax.jit(fn)

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Run the kernel (grid/block dims are accepted for API parity; the
        Mosaic compiler owns the schedule on TPU)."""
        raw = [a._data if isinstance(a, NDArray) else a for a in args]
        out = self._jitted(*raw)
        if isinstance(out, tuple):
            return tuple(NDArray(o) for o in out)
        return NDArray(out)

    def __call__(self, *args):
        return self.launch(args)


# Alias kept so reference scripts that import CudaModule keep working; the
# "source" they pass must be Python callables rather than CUDA text.
CudaModule = PallasModule
