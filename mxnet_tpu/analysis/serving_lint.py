"""Serving lint: can this Symbol be served recompile-free from buckets,
and does the fleet's admission control actually hold?

``mxnet_tpu.serving.ModelRunner`` pads every request batch up to a fixed
bucket ladder so steady-state traffic hits a finite, pre-compiled program
family.  That contract only holds for *batch-polymorphic* graphs: scaling
the data batch axis must scale every downstream shape proportionally.
Two classes break it —

- **data-dependent / baked shapes** (SRV001, error): shape inference
  fails when the batch size changes, or an output's batch axis does not
  follow the input's (a static Reshape collapsed it, a value-dependent
  geometry leaked in).  Such a symbol recompiles — or silently mixes
  rows — per request geometry; the runner refuses to serve it.
- **static Reshape on the batch path** (SRV002, warning): the graph may
  still infer, but each bucket traces a distinct program through the
  baked shape; use 0/-1 dim codes.

The probe is pure shape inference (no tracing), so it is safe to run at
model-load time inside the server.

The fleet rules (SRV004, error) keep multi-model admission control a
*static* problem:

- **packing** (:func:`lint_fleet_hbm`): the summed modeled peak HBM of a
  fleet registration set against the cap — ``ModelFleet.register``
  refuses an over-cap registration with these findings rendered into the
  error, so over-commit is caught at load, not at the first OOM;
- **deadline propagation** (:func:`lint_deadline_propagation`): a pure
  AST scan for request paths that bind ``deadline_ms`` but call a
  ``submit()``/``infer()`` sink without passing it on — such a request
  can never be shed and rots in the queue, exactly the queue-collapse
  mode the SLO tiers exist to prevent.  ``--self-check`` sweeps it over
  every shipped serving source (``mxnet_tpu/serving/``,
  ``tools/serve.py``, ``examples/serving/``).

The decode rule (SRV006, error — the decode twin of SRV001/SRV002)
keeps the autoregressive tier recompile-free:

- **trace-constant geometry** (:func:`lint_decode_trace_constants`): in
  a decode/prefill function that touches jax, Python ``if``/``while``/
  ``for range(...)`` control flow — or slice bounds — over
  sequence-geometry names (``length``/``position``/``offset``/...)
  bakes that value into the compiled program as a constant: one program
  per length (a recompile per request geometry) or a silently-wrong
  reuse.  Geometry must stay in traced ops — masks, ``jnp.where``,
  ``take_along_axis`` — which is exactly how
  ``transformer/decode.py`` spells both phases.  ``--self-check``
  sweeps ``mxnet_tpu/serving/`` + ``mxnet_tpu/transformer/decode.py``.
"""
from __future__ import annotations

import ast

from .findings import Finding, filter_findings

__all__ = ["lint_serving", "lint_fleet_hbm", "lint_deadline_propagation",
           "lint_decode_trace_constants"]

# mirrors graph_lint._RESHAPE_OPS; serving cares about the batch axis
_RESHAPE_OPS = frozenset({"Reshape", "reshape"})


def _scaled(shapes, factor):
    return {name: (int(s[0]) * factor,) + tuple(s[1:])
            for name, s in shapes.items()}


def _infer(symbol, shapes):
    try:
        arg_shapes, out_shapes, _aux = symbol.infer_shape(**shapes)
    except Exception as e:
        return None, str(e)
    if arg_shapes is None or out_shapes is None:
        return None, "shape inference is underdetermined"
    return out_shapes, None


def _lint_batch_polymorphism(symbol, data_shapes):
    """Scale the data batch axis and require every output batch axis to
    follow proportionally (the padded-bucket execution model)."""
    base = {name: tuple(s) for name, s in data_shapes.items()}
    if not base or any(len(s) == 0 for s in base.values()):
        return []
    subject = symbol.name or "<graph>"
    out0, err = _infer(symbol, base)
    if err is not None:
        return [Finding("SRV001", subject,
                        "shape inference fails at the declared data "
                        "shapes %r: %s" % (base, err))]
    factor = 2
    out1, err = _infer(symbol, _scaled(base, factor))
    if err is not None:
        return [Finding("SRV001", subject,
                        "scaling the batch axis by %d breaks shape "
                        "inference (%s) — requests of different sizes "
                        "cannot share padded buckets" % (factor, err))]
    findings = []
    names = symbol.list_outputs()
    for i, (s0, s1) in enumerate(zip(out0, out1)):
        if not s0:
            continue
        want = (int(s0[0]) * factor,) + tuple(s0[1:])
        if tuple(s1) != want:
            findings.append(Finding(
                "SRV001", names[i] if i < len(names) else subject,
                "output %d has shape %r at batch %r but %r at batch x%d "
                "(expected %r): the batch axis is baked or data-"
                "dependent, so bucket padding would mix rows or "
                "recompile per request size"
                % (i, tuple(s0), {k: v[0] for k, v in base.items()},
                   tuple(s1), factor, want)))
    return findings


def _lint_static_batch_reshape(symbol):
    out = []
    for n in symbol._nodes():
        if n.op not in _RESHAPE_OPS:
            continue
        from ..ops import registry as _reg
        shape = _reg.canonicalize(n.attrs.get("shape", ()))
        if not isinstance(shape, (tuple, list)) or not shape:
            continue
        lead = shape[0]
        if isinstance(lead, int) and lead > 0:
            out.append(Finding(
                "SRV002", n.name,
                "Reshape target %r bakes the batch dimension to %d; each "
                "serving bucket traces its own program (or fails) — use "
                "dim code 0 (copy) or -1 (infer) for the batch axis"
                % (tuple(shape), lead)))
    return out


def _lint_bucket_hbm(symbol, data_shapes, buckets, cap_bytes):
    """SRV003: per-bucket modeled peak HBM (static cost pass) vs a
    configurable cap — catches the bucket ladder OOMing at load, with no
    device attached."""
    from .cost import analyze_symbol
    out = []
    subject = symbol.name or "<graph>"
    for b in sorted(set(int(x) for x in buckets)):
        shapes = {name: (b,) + tuple(s[1:])
                  for name, s in data_shapes.items()}
        report = analyze_symbol(symbol, shapes=shapes)
        if report is None:
            continue
        if report.peak_hbm_bytes > cap_bytes:
            out.append(Finding(
                "SRV003", "%s[bucket=%d]" % (subject, b),
                "modeled peak HBM %.1f MiB exceeds the %.1f MiB cap — "
                "the bucket would OOM (or page) at warmup; shrink the "
                "bucket ladder or raise the cap"
                % (report.peak_hbm_bytes / (1 << 20),
                   cap_bytes / (1 << 20))))
    return out


def lint_fleet_hbm(models, cap_bytes):
    """SRV004 (packing half): ``models`` maps model name -> modeled peak
    HBM bytes (None = unmodelable, excluded from the sum with a note);
    the sum of the known figures must fit ``cap_bytes``.  Called by
    ``ModelFleet.register`` on every registration — admission control as
    a static problem, refused with the modeled numbers in hand."""
    if not cap_bytes:
        return []
    known = {n: int(b) for n, b in models.items() if b}
    total = sum(known.values())
    if total <= int(cap_bytes):
        return []
    detail = ", ".join("%s=%.1f MiB" % (n, b / (1 << 20))
                       for n, b in sorted(known.items()))
    unmodeled = sorted(n for n, b in models.items() if not b)
    if unmodeled:
        detail += "; unmodeled (not counted): %s" % ", ".join(unmodeled)
    return [Finding(
        "SRV004", "fleet",
        "summed modeled peak HBM %.1f MiB exceeds the %.1f MiB cap "
        "(%s) — the fleet would OOM under concurrent load; drop a "
        "model, shrink its bucket ladder, or raise the cap"
        % (total / (1 << 20), int(cap_bytes) / (1 << 20), detail))]


_SUBMIT_SINKS = frozenset({"submit", "infer"})


def _bound_names(fn):
    names = {a.arg for a in fn.args.args}
    names.update(a.arg for a in fn.args.kwonlyargs)
    names.update(a.arg for a in getattr(fn.args, "posonlyargs", ()))
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr)):
            t = node.target
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def lint_deadline_propagation(path=None, source=None):
    """SRV004 (propagation half): flag functions that bind a
    ``deadline_ms`` name (parameter or assignment) yet call a
    ``.submit(...)`` / ``.infer(...)`` sink without a ``deadline_ms``
    keyword (a ``**kwargs`` splat counts as propagating).  Pure AST —
    no imports of the target."""
    if source is None:
        with open(path, "r") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path or "<string>")
    except SyntaxError as e:
        return [Finding("SRV004", path or "<string>",
                        "source does not parse: %s" % e)]
    subject = path or "<string>"
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if "deadline_ms" not in _bound_names(fn):
            continue
        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in _SUBMIT_SINKS):
                continue
            kwargs = {k.arg for k in call.keywords}
            if "deadline_ms" in kwargs or None in kwargs:
                continue
            out.append(Finding(
                "SRV004", "%s:%d" % (subject, call.lineno),
                "%s() binds deadline_ms but calls .%s() without "
                "propagating it — the request carries no deadline, so "
                "admission control can never shed it and it rots in "
                "the queue under overload"
                % (fn.name, call.func.attr)))
    return out


import re as _re

# functions the decode rule inspects: anything that names itself a
# prefill/decode path AND touches jax (host-side helpers that never
# trace are exempt by the jax-reference requirement)
_SRV006_FN = _re.compile(r"(prefill|decode)", _re.I)
_SRV006_JAX = frozenset({"jax", "jnp", "lax"})
# sequence-geometry identifier segments: an identifier counts when any
# "_"-separated segment is one of these (so `lengths`, `q_pos` match;
# `n_layers`, `page_size`, `Tb`, bare `len(...)` do not) — plus the
# joined compound spellings below (`seq_len`, `cached_len`, ...)
_SRV006_GEOM = frozenset({
    "length", "lengths", "seqlen", "pos", "position", "positions",
    "offset", "offsets", "ntokens", "promptlen"})
_SRV006_GEOM_JOINED = frozenset({
    "seqlen", "ntokens", "promptlen", "cachedlen", "tokenpos"})


def _srv006_geometry(name):
    segs = name.lower().split("_")
    if any(s in _SRV006_GEOM for s in segs):
        return True
    return name.lower().replace("_", "") in _SRV006_GEOM_JOINED


def _srv006_names(node):
    """Geometry identifiers referenced anywhere under ``node`` —
    bare names and terminal attribute names (``self.cached_len``)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and _srv006_geometry(n.id):
            out.append(n.id)
        elif isinstance(n, ast.Attribute) and _srv006_geometry(n.attr):
            out.append(n.attr)
    return out


def lint_decode_trace_constants(path=None, source=None):
    """SRV006: flag decode/prefill functions that put sequence geometry
    into Python control flow or slice bounds (module docstring).  Pure
    AST; ``# mxlint: disable=SRV006`` on the offending line waives a
    deliberate host-side exception."""
    from .source_lint import _line_suppressions
    if source is None:
        with open(path, "r") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path or "<string>")
    except SyntaxError as e:
        return [Finding("SRV006", path or "<string>",
                        "source does not parse: %s" % e)]
    suppressed = _line_suppressions(source)
    subject = path or "<string>"
    out = []

    def emit(fn, node, what, names):
        if "SRV006" in suppressed.get(node.lineno, ()):
            return
        out.append(Finding(
            "SRV006", "%s:%d" % (subject, node.lineno),
            "%s() bakes sequence geometry into the trace: %s over %s — "
            "the compiled program pins that value as a constant, so "
            "serving recompiles per request geometry (or reuses the "
            "wrong program); move it into traced ops (a position mask, "
            "jnp.where, take_along_axis)"
            % (fn.name, what, ", ".join(sorted(set(names))))))

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _SRV006_FN.search(fn.name):
            continue
        refs = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
        refs |= {n.value.id for n in ast.walk(fn)
                 if isinstance(n, ast.Attribute)
                 and isinstance(n.value, ast.Name)}
        if not (refs & _SRV006_JAX):
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                names = _srv006_names(node.test)
                if names:
                    emit(fn, node,
                         "`%s` branching" % type(node).__name__.lower(),
                         names)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                if isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Name) and \
                        it.func.id == "range":
                    names = [x for a in it.args
                             for x in _srv006_names(a)]
                    if names:
                        emit(fn, node, "`for range(...)` iteration",
                             names)
            elif isinstance(node, ast.Slice):
                names = [x for part in
                         (node.lower, node.upper, node.step)
                         if part is not None
                         for x in _srv006_names(part)]
                if names:
                    emit(fn, node, "slice bounds", names)
    return out


def lint_serving(symbol, data_shapes=None, disable=(), buckets=None,
                 hbm_cap_bytes=None):
    """Run the serving rules over ``symbol``.

    ``data_shapes``: {data_name: full shape incl. batch axis}.  Without
    it only the structural SRV002 scan runs (the polymorphism probe
    needs a concrete batch axis to scale).  With ``hbm_cap_bytes`` set,
    the modeled peak HBM of every bucket (``buckets`` defaults to the
    declared batch axis alone) is checked against the cap (SRV003).
    """
    findings = _lint_static_batch_reshape(symbol)
    if data_shapes:
        findings += _lint_batch_polymorphism(symbol, data_shapes)
        if hbm_cap_bytes:
            bk = buckets if buckets else [
                next(iter(data_shapes.values()))[0]]
            findings += _lint_bucket_hbm(symbol, data_shapes, bk,
                                         int(hbm_cap_bytes))
    return filter_findings(findings, disable)
