"""mxcost: static per-op cost/memory analysis over closed jaxprs.

TVM (PAPERS.md) drives its optimizing compiler with a learned cost
model; XLA exposes a post-compile ``cost_analysis()`` — but both need a
working backend.  This module is the hardware-free counterpart: an
abstract interpreter over a ``ClosedJaxpr`` that never executes (and
never compiles) anything, so it runs on the 1-core CI host even when the
TPU is down (the BENCH_r05 failure mode).  It produces, per primitive
and per program:

- **flops** / **transcendentals** — counted with the same conventions as
  XLA's HLO cost analysis (2·M·N·K dots, padding-blind convs, tree-free
  ``in-out`` reduces, 1/elem arithmetic), cross-validated on CPU against
  ``jit(f).lower().compile().cost_analysis()`` within ``XLA_FLOP_RTOL``;
- **bytes read / written** — unfused upper bound: every eqn reads its
  operand avals and writes its outputs (XLA fusion only lowers this);
- **host↔device transfer bytes** — caller classifies which invars are
  host-fed and which outputs are fetched;
- **collective bytes per mesh axis** — ring formulas over explicit
  ``psum``/``all_gather``/… eqns (trace with ``axis_env`` to get them);
- **peak HBM** — liveness walk over the (recursively inlined) eqn tape:
  non-donated inputs and consts are resident for the whole program,
  donated inputs die at last use, intermediates die at last use.

Everything is deterministic (``--self-check`` asserts two runs produce
identical reports) and pure-Python over aval metadata, so the checked-in
``STATIC_BUDGETS.json`` can gate PRs in CI with no accelerator attached.
"""
from __future__ import annotations

import math

import numpy as _np

__all__ = ["CostReport", "TapeOp", "build_tape", "analyze_jaxpr",
           "analyze_fn", "analyze_symbol", "XLA_FLOP_RTOL",
           "collective_bytes", "ring_bytes_per_axis",
           "unpriced_findings", "TRANSCENDENTALS",
           "KERNEL_COSTS", "declare_kernel_cost", "kernel_name_of"]

# documented cross-validation tolerance: |modeled - xla| / xla for the
# golden single-primitive programs of tests/test_analysis.py on the CPU
# backend.  The residual is XLA being padding-aware for SAME convs and
# power-of-two rounding in tree reduces; dots match exactly.
XLA_FLOP_RTOL = 0.05

# elementwise primitives costed as transcendentals (XLA's separate
# counter), not flops
TRANSCENDENTALS = frozenset({
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "sin", "cos", "tan",
    "asin", "acos", "atan", "atan2", "sinh", "cosh", "asinh", "acosh",
    "atanh", "erf", "erfc", "erf_inv", "logistic", "rsqrt", "sqrt",
    "cbrt", "pow", "lgamma", "digamma",
})

# zero-arithmetic data movement: bytes, no flops
_MOVEMENT = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad", "rev",
    "gather", "convert_element_type", "bitcast_convert_type", "iota",
    "copy", "select_n", "stop_gradient", "split", "expand_dims",
    "device_put", "real", "imag", "sharding_constraint",
})

# collective primitives and their per-device wire-bytes model over a
# group of size K (ring algorithms; docs/analysis.md "Cost model").  A
# grouped reduction (``psum`` over several axes at once) is priced as ONE
# ring over the combined group (K = product of the axis sizes) — XLA
# lowers a multi-axis reduction to a single replica group, not a
# hierarchy — and the total is attributed per axis proportionally to
# each axis's (size − 1) share (the marginal ring length it adds):
#   psum (all-reduce)     2·(K-1)/K · payload
#   all_gather            (K-1)/K · output   (output = K · input)
#   reduce_scatter        (K-1)/K · input
#   all_to_all            (K-1)/K · payload
#   ppermute              payload  (one hop; a ring is K scanned hops)
_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "all_gather", "reduce_scatter", "all_to_all",
    "ppermute", "pbroadcast",
})

# primitives that carry a mesh-axis name but move nothing over the wire
# (axis arithmetic / replication-type casts) — they must NOT be flagged
# as unpriced collectives
_AXIS_LOCAL = frozenset({
    "axis_index", "pvary", "psum_invariant", "pbroadcast_invariant",
    "sharding_constraint",
})


def _numel(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _aval_bytes(aval):
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    try:
        itemsize = _np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (custom PRNG keys): key data is uint32[2]
        itemsize = 8
    return _numel(shape) * itemsize


def collective_bytes(prim, payload_bytes, axis_size, out_bytes=None):
    """Per-device wire bytes for one collective over a group of size K.

    ``payload_bytes`` is the operand (input) size; ``out_bytes`` the
    result size where the formula needs it (``all_gather`` moves the
    *output* — defaults to ``K · payload`` for it, tiled semantics).
    """
    k = max(int(axis_size), 1)
    if k == 1:
        return 0
    if prim in ("psum", "pmax", "pmin"):
        return int(2 * (k - 1) * payload_bytes // k)
    if prim == "all_gather":
        out = payload_bytes * k if out_bytes is None else out_bytes
        return int((k - 1) * out // k)
    if prim in ("reduce_scatter", "all_to_all", "pbroadcast"):
        return int((k - 1) * payload_bytes // k)
    return int(payload_bytes)


def ring_bytes_per_axis(prim, in_bytes, out_bytes, axis_sizes):
    """{axis: wire bytes} for one collective over the (possibly grouped)
    axes in ``axis_sizes`` — one ring over the combined group
    K = Π sizes, attributed per axis proportionally to (size − 1), the
    marginal ring length each axis contributes (remainder bytes go to
    the first axis in sorted order, keeping the split deterministic and
    the per-axis sum exactly equal to the group total)."""
    sizes = {ax: max(int(s), 1) for ax, s in axis_sizes.items()}
    group = 1
    for s in sizes.values():
        group *= s
    total = collective_bytes(prim, in_bytes, group, out_bytes=out_bytes)
    if total == 0 or not sizes:
        return {ax: 0 for ax in sizes}
    weights = {ax: s - 1 for ax, s in sizes.items()}
    wsum = sum(weights.values())
    if wsum == 0:
        return {ax: 0 for ax in sizes}
    out = {}
    assigned = 0
    for ax in sorted(sizes)[1:]:
        out[ax] = total * weights[ax] // wsum
        assigned += out[ax]
    first = sorted(sizes)[0]
    out[first] = total - assigned
    return out


def _axis_names(params):
    axes = params.get("axes", params.get("axis_name", ()))
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list)):
        return tuple(a for a in axes if isinstance(a, str))
    return (axes,)


# ---------------------------------------------------------------------------
# kernel-declared cost models for pallas_call
# ---------------------------------------------------------------------------
# kernel fn name -> cost fn(eqn) -> {"flops", "transcendentals",
# "bytes_read", "bytes_written"}.  A ``pallas_call`` severs jaxpr
# dataflow (the kernel body sees refs, not the call operands) and its
# body is traced once — not once per grid step — so walking it prices
# the kernel wrong in BOTH directions.  A shipped kernel therefore
# DECLARES its cost here (shape arithmetic over the eqn's operand avals
# + grid, deterministic); the tape consults the registry BEFORE falling
# back to the body-walk + zero-cost connector, and an unannotated
# shipped kernel is NAMED (``Tape.unpriced_kernels`` -> COST005) instead
# of silently costing near-zero.  Keying is the kernel *function name*
# (``name_and_src_info.name``, stable through functools.partial), the
# same name the ``lint_kernel_costs`` AST sweep resolves.
KERNEL_COSTS = {}


def declare_kernel_cost(kernel_name):
    """Decorator: register ``fn(eqn) -> cost dict`` for a Pallas kernel
    (keyed by the kernel function's name as it appears in the traced
    ``pallas_call`` eqn)."""
    def wrap(fn):
        KERNEL_COSTS[str(kernel_name)] = fn
        return fn
    return wrap


def kernel_name_of(eqn):
    """The kernel function name of a traced ``pallas_call`` eqn (the
    registry key), or None when it cannot be determined."""
    nsi = eqn.params.get("name_and_src_info")
    name = getattr(nsi, "name", None)
    if name:
        return str(name)
    return None


def _grid_of(eqn):
    grid = getattr(eqn.params.get("grid_mapping"), "grid", ()) or ()
    return tuple(int(g) for g in grid if isinstance(g, int))


# ---------------------------------------------------------------------------
# per-primitive flop models
# ---------------------------------------------------------------------------
def _dot_general_flops(eqn):
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = _numel([lhs.shape[d] for d in lb])
    contract = _numel([lhs.shape[d] for d in lc])
    lfree = _numel([d for i, d in enumerate(lhs.shape)
                    if i not in set(lc) | set(lb)])
    rfree = _numel([d for i, d in enumerate(rhs.shape)
                    if i not in set(rc) | set(rb)])
    return 2 * batch * lfree * rfree * contract


def _conv_flops(eqn):
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params["dimension_numbers"]
    rhs_spec = dn.rhs_spec  # (out_c, in_c, *spatial)
    in_c = int(rhs.shape[rhs_spec[1]])
    kernel_spatial = _numel([rhs.shape[d] for d in rhs_spec[2:]])
    groups = int(eqn.params.get("feature_group_count", 1)) or 1
    # in_c here is already per-group (rhs carries IC/groups), so no
    # further division; batch_group_count folds into the out numel
    del groups
    return 2 * _numel(out.shape) * in_c * kernel_spatial


def _eqn_cost(eqn):
    """(flops, transcendentals) for one eqn — shapes only, no values."""
    prim = eqn.primitive.name
    out_n = sum(_numel(getattr(v.aval, "shape", ())) for v in eqn.outvars)
    in_n = sum(_numel(getattr(v.aval, "shape", ())) for v in eqn.invars)
    if prim == "dot_general":
        return _dot_general_flops(eqn), 0
    if prim == "conv_general_dilated":
        return _conv_flops(eqn), 0
    if prim in TRANSCENDENTALS:
        return 0, out_n
    if prim in _MOVEMENT:
        return 0, 0
    if prim.startswith("reduce_window"):
        window = _numel(eqn.params.get("window_dimensions", ()))
        return out_n * max(window - 1, 1), 0
    if prim.startswith("reduce_") or prim in ("argmax", "argmin"):
        return max(in_n - out_n, 0), 0
    if prim == "select_and_scatter_add":
        return in_n, 0
    if prim.startswith("scatter"):
        updates = _numel(getattr(eqn.invars[-1].aval, "shape", ()))
        return updates if prim != "scatter" else 0, 0
    if prim.startswith("cum"):
        return in_n, 0
    if prim == "sort":
        n = max(out_n, 2)
        return int(n * math.ceil(math.log2(n))), 0
    if prim in _COLLECTIVES:
        # the arithmetic of an all-reduce is counted; wire bytes are
        # tracked separately in TapeOp.collective
        return out_n if prim in ("psum", "pmax", "pmin") else 0, 0
    if prim == "integer_pow":
        return out_n, 0
    # default: one arithmetic op per output element (add/mul/compare/...)
    return out_n, 0


# ---------------------------------------------------------------------------
# the tape: recursively inlined eqn sequence with stable var ids
# ---------------------------------------------------------------------------
class TapeOp:
    """One (inlined) eqn: primitive, scaled cost, operand/result ids."""
    __slots__ = ("prim", "scale", "in_ids", "out_ids", "flops",
                 "transcendentals", "bytes_read", "bytes_written",
                 "collective", "axes", "params")

    def __init__(self, prim, scale, in_ids, out_ids, flops, trans,
                 bytes_read, bytes_written, collective, axes, params):
        self.prim = prim
        self.scale = scale
        self.in_ids = in_ids
        self.out_ids = out_ids
        self.flops = flops
        self.transcendentals = trans
        self.bytes_read = bytes_read
        self.bytes_written = bytes_written
        self.collective = collective  # {axis_name: bytes}
        self.axes = axes
        self.params = params


class Tape:
    """Flat program tape + var table, shared by the cost totals, the
    liveness walk, the DST variance pass and the mxshard propagation."""

    def __init__(self):
        self.ops = []            # [TapeOp]
        self.avals = {}          # id -> aval
        self.invar_ids = []      # program inputs, in order
        self.outvar_ids = []     # program outputs, in order
        self.const_ids = []      # closure constants
        self.literal_ids = set()  # inline literals (e.g. the 1 in psum(1))
        self.literal_values = {}  # id -> literal value (mxgen emits these)
        self.unpriced = []       # [(prim, axis, reason)] — COST004 feed
        self.unpriced_kernels = []  # [kernel name] — COST005 feed
        self.unbounded_loops = False
        self._next = 0

    def fresh(self, aval, literal=False):
        i = self._next
        self._next += 1
        self.avals[i] = aval
        if literal:
            self.literal_ids.add(i)
        return i


def _sub_jaxprs(params):
    """(name, ClosedJaxpr/Jaxpr) children of an eqn's params."""
    out = []
    for k, v in params.items():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                out.append((k, item.jaxpr, item.consts))
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                out.append((k, item, ()))
    return out


def build_tape(closed_jaxpr, axis_sizes=None):
    """Inline a ClosedJaxpr (through pjit / custom_jvp / remat / scan /
    cond / while) into a flat Tape.  ``axis_sizes`` maps mesh-axis name →
    size for the collective-bytes model (defaults to the jaxpr's bound
    axis sizes where visible, else 1)."""
    import jax

    axis_sizes = dict(axis_sizes or {})
    tape = Tape()

    def read(env, atom):
        if isinstance(atom, jax.core.Literal):
            i = tape.fresh(atom.aval, literal=True)
            tape.literal_values[i] = atom.val
            return i
        return env[atom]

    def bind_out(env, var):
        i = tape.fresh(var.aval)
        env[var] = i
        return i

    def walk(jaxpr, consts, env, scale):
        for cv, cval in zip(jaxpr.constvars, consts):
            if cv not in env:
                i = tape.fresh(cv.aval)
                env[cv] = i
                tape.const_ids.append(i)
        for eqn in jaxpr.eqns:
            subs = _sub_jaxprs(eqn.params)
            prim = eqn.primitive.name
            if subs:
                _walk_call(prim, eqn, subs, env, scale)
                continue
            in_ids = tuple(read(env, a) for a in eqn.invars)
            out_ids = tuple(bind_out(env, v) for v in eqn.outvars)
            flops, trans = _eqn_cost(eqn)
            br = sum(_aval_bytes(a.aval) for a in eqn.invars)
            bw = sum(_aval_bytes(v.aval) for v in eqn.outvars)
            coll = {}
            eqn_axes = _axis_names(eqn.params)
            if prim in _COLLECTIVES:
                payload = sum(_aval_bytes(a.aval) for a in eqn.invars)
                out_payload = sum(_aval_bytes(v.aval) for v in eqn.outvars)
                declared = {ax: axis_sizes[ax] for ax in eqn_axes
                            if ax in axis_sizes}
                for ax in eqn_axes:
                    if ax not in axis_sizes:
                        # an undeclared axis defaults to size 1: the
                        # collective would silently price at ZERO bytes —
                        # name it so COST004 can surface the hole
                        tape.unpriced.append(
                            (prim, ax, "axis size undeclared"))
                coll = ring_bytes_per_axis(prim, payload, out_payload,
                                           declared)
            elif eqn_axes and prim not in _AXIS_LOCAL:
                # a primitive that names mesh axes but has no wire-bytes
                # model: whatever it moves contributes zero to the
                # collective totals — flag instead of staying silent
                for ax in eqn_axes:
                    tape.unpriced.append((prim, ax, "no cost model"))
            tape.ops.append(TapeOp(
                prim, scale, in_ids, out_ids, flops * scale, trans * scale,
                br * scale, bw * scale,
                {k: v * scale for k, v in coll.items()},
                _axis_names(eqn.params), eqn.params))

    def _walk_call(prim, eqn, subs, env, scale):
        """Inline one call-like eqn.  The common case (pjit, custom_jvp,
        custom_vjp primal, remat, closed_call) maps call operands 1:1
        onto the sub-jaxpr's invars; scan/while/cond get structural
        handling; anything else is traversed with fresh inner inputs
        (cost still counted, liveness approximate)."""
        import jax

        if prim == "pallas_call":
            # declared-cost fast path: one priced op with REAL dataflow
            # (in place of the body walk, whose once-not-per-grid-step
            # trace misprices the kernel, plus the zero-cost connector)
            kname = kernel_name_of(eqn)
            cost_fn = KERNEL_COSTS.get(kname)
            if cost_fn is not None:
                cost = cost_fn(eqn)
                in_ids = tuple(read(env, a) for a in eqn.invars)
                out_ids = tuple(bind_out(env, v) for v in eqn.outvars)
                tape.ops.append(TapeOp(
                    prim, scale, in_ids, out_ids,
                    int(cost.get("flops", 0)) * scale,
                    int(cost.get("transcendentals", 0)) * scale,
                    int(cost.get("bytes_read", 0)) * scale,
                    int(cost.get("bytes_written", 0)) * scale,
                    {}, (), {"kernel": kname}))
                return
            tape.unpriced_kernels.append(kname or "<anonymous>")

        sub_scale = scale
        if prim == "scan":
            sub_scale = scale * max(int(eqn.params.get("length", 1)), 1)
        elif prim == "while":
            tape.unbounded_loops = True
        if prim == "cond":
            # deterministic: charge the most expensive branch
            best, best_cost = None, -1
            for _, sj, sc in subs:
                t2 = build_tape(
                    jax.core.ClosedJaxpr(sj, list(sc)), axis_sizes)
                cost = sum(op.flops for op in t2.ops)
                if cost > best_cost:
                    best, best_cost = (sj, sc), cost
            subs = [("branches", best[0], best[1])]
            operand_atoms = eqn.invars[1:]  # drop the predicate
        else:
            operand_atoms = eqn.invars

        connected = True
        for si, (_, sj, sc) in enumerate(subs):
            inner_env = {}
            n = len(sj.invars)
            if prim == "while":
                # cond_jaxpr and body_jaxpr both take the carry
                atoms = operand_atoms[-n:] if len(operand_atoms) >= n else ()
            elif prim == "custom_jvp_call" and si > 0:
                atoms = ()   # only the primal call_jaxpr is costed
            else:
                atoms = operand_atoms[:n] \
                    if len(operand_atoms) >= n else ()
            def _same_aval(a, b):
                return (getattr(a, "shape", None) == getattr(b, "shape",
                                                             None)
                        and getattr(a, "dtype", None) == getattr(
                            b, "dtype", None))

            if len(atoms) == n:
                for var, atom in zip(sj.invars, atoms):
                    if _same_aval(var.aval, getattr(atom, "aval", None)):
                        inner_env[var] = read(env, atom)
                    else:
                        # aval mismatch (scan's full-xs operand vs the
                        # body's per-iteration slice var): binding them
                        # to ONE id would fake dataflow — e.g. a chain
                        # "reading" the stacked array inside the body.
                        # Sever the edge; the connector op below keeps
                        # liveness sound
                        inner_env[var] = tape.fresh(var.aval)
                        if si == 0:
                            connected = False
            else:
                for var in sj.invars:
                    inner_env[var] = tape.fresh(var.aval)
                if si == 0:
                    connected = False
            walk(sj, list(sc), inner_env, sub_scale)
            if si == 0 and len(sj.outvars) == len(eqn.outvars):
                for outer, inner in zip(eqn.outvars, sj.outvars):
                    if isinstance(inner, jax.core.Literal) or \
                            not _same_aval(outer.aval, inner.aval):
                        # stacked scan output vs the body's slice var:
                        # same severing rule as the operands above
                        env[outer] = tape.fresh(outer.aval)
                        if not isinstance(inner, jax.core.Literal):
                            connected = False
                    else:
                        env[outer] = inner_env.get(
                            inner, tape.fresh(inner.aval))
            elif si == 0:
                for outer in eqn.outvars:
                    env[outer] = tape.fresh(outer.aval)
                connected = False
            if prim == "custom_jvp_call":
                break   # don't double-count the jvp rule
        if not connected:
            # a call whose operands/results could not be mapped 1:1
            # onto its sub-jaxpr (pallas_call's ref-passing kernels):
            # the body's COST is already on the tape, but its dataflow
            # is severed — append a zero-cost connector op so liveness
            # and the shard/variance propagation still see that the
            # outputs derive from the operands
            tape.ops.append(TapeOp(
                prim, scale,
                tuple(read(env, a) for a in eqn.invars),
                tuple(env[v] for v in eqn.outvars),
                0, 0, 0, 0, {}, (), {}))

    env = {}
    jaxpr = closed_jaxpr.jaxpr
    for v in jaxpr.invars:
        i = tape.fresh(v.aval)
        env[v] = i
        tape.invar_ids.append(i)
    walk(jaxpr, list(closed_jaxpr.consts), env, 1)
    for v in jaxpr.outvars:
        import jax as _jax
        if isinstance(v, _jax.core.Literal):
            tape.outvar_ids.append(tape.fresh(v.aval))
        else:
            tape.outvar_ids.append(env[v])
    return tape


# ---------------------------------------------------------------------------
# liveness → peak-HBM estimate
# ---------------------------------------------------------------------------
def _peak_hbm(tape, donated_ids):
    """Max over program points of resident bytes: consts + non-donated
    inputs live throughout; donated inputs and intermediates die at their
    last use; outputs live from definition to program end."""
    donated = set(donated_ids)
    out_ids = set(tape.outvar_ids)
    last_use = {}
    for t, op in enumerate(tape.ops):
        for i in op.in_ids:
            last_use[i] = t
    for i in tape.outvar_ids:
        last_use[i] = len(tape.ops)  # outputs survive the program

    resident = 0   # consts + non-donated inputs: the whole program
    for i in tape.const_ids:
        resident += _aval_bytes(tape.avals[i])
    live = {}
    for i in tape.invar_ids:
        b = _aval_bytes(tape.avals[i])
        if i in donated:
            live[i] = b
        else:
            resident += b
    peak = resident + sum(live.values())
    for t, op in enumerate(tape.ops):
        for i in op.out_ids:
            if i in last_use or i in out_ids:
                live[i] = _aval_bytes(tape.avals[i])
        cur = resident + sum(live.values())
        if cur > peak:
            peak = cur
        for i in list(live):
            if last_use.get(i, -1) <= t and i not in out_ids:
                del live[i]
    return peak


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------
class CostReport:
    """Deterministic cost/memory summary of one program.

    ``as_dict()`` is the stable JSON surface (documented in
    docs/analysis.md): all counters are plain ints, dict keys sorted.
    """

    def __init__(self, per_primitive, flops, transcendentals, bytes_read,
                 bytes_written, transfer_h2d_bytes, transfer_d2h_bytes,
                 collective_bytes_per_axis, peak_hbm_bytes, input_bytes,
                 output_bytes, const_bytes, n_eqns, axis_sizes,
                 unbounded_loops=False, unpriced_collectives=(),
                 unpriced_kernels=()):
        self.per_primitive = per_primitive
        self.flops = flops
        self.transcendentals = transcendentals
        self.bytes_read = bytes_read
        self.bytes_written = bytes_written
        self.transfer_h2d_bytes = transfer_h2d_bytes
        self.transfer_d2h_bytes = transfer_d2h_bytes
        self.collective_bytes_per_axis = collective_bytes_per_axis
        self.peak_hbm_bytes = peak_hbm_bytes
        self.input_bytes = input_bytes
        self.output_bytes = output_bytes
        self.const_bytes = const_bytes
        self.n_eqns = n_eqns
        self.axis_sizes = axis_sizes
        self.unbounded_loops = unbounded_loops
        # [(prim, axis, reason)]: collectives whose modeled wire bytes
        # are silently zero (unknown primitive / undeclared axis size)
        self.unpriced_collectives = list(unpriced_collectives)
        # [kernel name]: pallas_call kernels with no declared cost model
        # (priced off a once-per-trace body walk — wrong both ways)
        self.unpriced_kernels = list(unpriced_kernels)

    @property
    def transfer_bytes(self):
        return self.transfer_h2d_bytes + self.transfer_d2h_bytes

    @property
    def collective_bytes(self):
        return sum(self.collective_bytes_per_axis.values())

    def as_dict(self):
        return {
            "flops": int(self.flops),
            "transcendentals": int(self.transcendentals),
            "bytes_read": int(self.bytes_read),
            "bytes_written": int(self.bytes_written),
            "transfer_h2d_bytes": int(self.transfer_h2d_bytes),
            "transfer_d2h_bytes": int(self.transfer_d2h_bytes),
            "transfer_bytes": int(self.transfer_bytes),
            "collective_bytes": int(self.collective_bytes),
            "collective_bytes_per_axis": {
                k: int(v) for k, v in
                sorted(self.collective_bytes_per_axis.items())},
            "peak_hbm_bytes": int(self.peak_hbm_bytes),
            "input_bytes": int(self.input_bytes),
            "output_bytes": int(self.output_bytes),
            "const_bytes": int(self.const_bytes),
            "n_eqns": int(self.n_eqns),
            "axis_sizes": {k: int(v)
                           for k, v in sorted(self.axis_sizes.items())},
            "unbounded_loops": bool(self.unbounded_loops),
            "unpriced_collectives": [
                {"prim": p, "axis": a, "reason": r}
                for p, a, r in sorted(set(self.unpriced_collectives))],
            "unpriced_kernels": sorted(set(self.unpriced_kernels)),
            "per_primitive": {
                prim: {k: int(v) for k, v in sorted(row.items())}
                for prim, row in sorted(self.per_primitive.items())},
        }

    def render(self, title="mxcost"):
        d = self.as_dict()
        lines = ["%s: %d eqn(s), %.3f GFLOP, peak HBM %.1f MiB" % (
            title, d["n_eqns"], d["flops"] / 1e9,
            d["peak_hbm_bytes"] / (1 << 20))]
        lines.append("  transfer %.2f MiB h2d + %.2f MiB d2h; collectives %s"
                     % (d["transfer_h2d_bytes"] / (1 << 20),
                        d["transfer_d2h_bytes"] / (1 << 20),
                        {k: "%.2f MiB" % (v / (1 << 20)) for k, v in
                         d["collective_bytes_per_axis"].items()} or "none"))
        top = sorted(self.per_primitive.items(),
                     key=lambda kv: (-kv[1]["flops"], kv[0]))[:12]
        for prim, row in top:
            lines.append("  %-24s x%-4d %12d flops %12d bytes" % (
                prim, row["count"], row["flops"],
                row["bytes_read"] + row["bytes_written"]))
        return "\n".join(lines)


def analyze_tape(tape, donated_ids=(), host_invar_ids=None,
                 fetched_outvar_ids=None):
    """Aggregate a Tape into a CostReport."""
    per_prim = {}
    flops = trans = br = bw = 0
    coll = {}
    for op in tape.ops:
        row = per_prim.setdefault(op.prim, {
            "count": 0, "flops": 0, "transcendentals": 0,
            "bytes_read": 0, "bytes_written": 0, "collective_bytes": 0})
        row["count"] += op.scale
        row["flops"] += op.flops
        row["transcendentals"] += op.transcendentals
        row["bytes_read"] += op.bytes_read
        row["bytes_written"] += op.bytes_written
        row["collective_bytes"] += sum(op.collective.values())
        flops += op.flops
        trans += op.transcendentals
        br += op.bytes_read
        bw += op.bytes_written
        for ax, b in op.collective.items():
            coll[ax] = coll.get(ax, 0) + b

    host = set(tape.invar_ids if host_invar_ids is None else host_invar_ids)
    fetched = set(tape.outvar_ids if fetched_outvar_ids is None
                  else fetched_outvar_ids)
    h2d = sum(_aval_bytes(tape.avals[i]) for i in tape.invar_ids
              if i in host)
    d2h = sum(_aval_bytes(tape.avals[i]) for i in set(tape.outvar_ids)
              if i in fetched)
    in_bytes = sum(_aval_bytes(tape.avals[i]) for i in tape.invar_ids)
    out_bytes = sum(_aval_bytes(tape.avals[i])
                    for i in set(tape.outvar_ids))
    const_bytes = sum(_aval_bytes(tape.avals[i]) for i in tape.const_ids)
    axis_sizes = {}
    for op in tape.ops:
        for ax in op.axes:
            axis_sizes.setdefault(ax, 0)
    return CostReport(
        per_primitive=per_prim, flops=flops, transcendentals=trans,
        bytes_read=br, bytes_written=bw, transfer_h2d_bytes=h2d,
        transfer_d2h_bytes=d2h, collective_bytes_per_axis=coll,
        peak_hbm_bytes=_peak_hbm(tape, donated_ids),
        input_bytes=in_bytes, output_bytes=out_bytes,
        const_bytes=const_bytes, n_eqns=len(tape.ops),
        axis_sizes=axis_sizes, unbounded_loops=tape.unbounded_loops,
        unpriced_collectives=tape.unpriced,
        unpriced_kernels=tape.unpriced_kernels)


def analyze_jaxpr(closed_jaxpr, axis_sizes=None, donated_invars=(),
                  host_invars=None, fetched_outvars=None):
    """CostReport for a ClosedJaxpr.

    ``donated_invars``/``host_invars``: iterables of flat invar indices
    (donated: freed at last use for the HBM walk; host: counted as
    host→device transfer).  ``fetched_outvars``: flat outvar indices
    fetched back to the host (default: all).
    """
    tape = build_tape(closed_jaxpr, axis_sizes=axis_sizes)
    don = [tape.invar_ids[i] for i in donated_invars
           if 0 <= i < len(tape.invar_ids)]
    host = None if host_invars is None else [
        tape.invar_ids[i] for i in host_invars
        if 0 <= i < len(tape.invar_ids)]
    fetched = None if fetched_outvars is None else [
        tape.outvar_ids[i] for i in fetched_outvars
        if 0 <= i < len(tape.outvar_ids)]
    report = analyze_tape(tape, donated_ids=don, host_invar_ids=host,
                          fetched_outvar_ids=fetched)
    if axis_sizes:
        report.axis_sizes = {k: int(v) for k, v in axis_sizes.items()}
    return report


def _flat_arg_ranges(args):
    """[(start, stop)) flat-leaf index range per positional arg."""
    import jax
    ranges = []
    start = 0
    for a in args:
        leaves = jax.tree_util.tree_leaves(a)
        ranges.append((start, start + len(leaves)))
        start += len(leaves)
    return ranges


def analyze_fn(fn, *args, axis_env=None, axis_sizes=None,
               donate_argnums=(), host_argnums=None, **kwargs):
    """Trace ``fn(*args, **kwargs)`` with ``jax.make_jaxpr`` (no
    execution, no compilation) and analyze the result.

    ``axis_env``: [(axis_name, size)] so explicit collectives
    (``lax.psum``/``pmean``) trace without a mesh; their sizes feed the
    collective-bytes model unless ``axis_sizes`` overrides.
    ``donate_argnums``/``host_argnums`` classify whole positional args.
    """
    import jax

    closed = jax.make_jaxpr(fn, axis_env=axis_env)(*args, **kwargs)
    # kwargs leaves flatten after the positionals; argnum classification
    # addresses positionals only (kwargs default to device-resident)
    ranges = _flat_arg_ranges(args)
    donated = [i for n in donate_argnums if n < len(ranges)
               for i in range(*ranges[n])]
    host = None
    if host_argnums is not None:
        host = [i for n in host_argnums if n < len(ranges)
                for i in range(*ranges[n])]
    sizes = dict(axis_env or [])
    sizes.update(axis_sizes or {})
    return analyze_jaxpr(closed, axis_sizes=sizes,
                         donated_invars=donated, host_invars=host)


def symbol_closed_jaxpr(symbol, shapes, type_dict=None, train=False):
    """Trace a Symbol's forward program at concrete ``shapes``:
    ``(closed_jaxpr, args, aux)`` with args/aux the name→
    ShapeDtypeStruct dicts (flat invar order follows their sorted
    keys), or None when the graph is underspecified or does not trace.
    Shared by :func:`analyze_symbol` and the fusion pass."""
    import jax

    from ..symbol.symbol import _infer_entry_shapes, make_graph_fn
    known = {k: tuple(v) for k, v in (shapes or {}).items()
             if v is not None}
    tdict = {k: _np.dtype(v) for k, v in (type_dict or {}).items()}
    entry_shapes, ok = _infer_entry_shapes(symbol._outputs, known, tdict)
    if not ok:
        return None
    args, aux = {}, {}
    for n in symbol._nodes():
        if n.op is not None:
            continue
        s = entry_shapes.get((id(n), 0))
        if s is None:
            return None
        (aux if n._is_aux else args)[n.name] = jax.ShapeDtypeStruct(
            tuple(s.shape), s.dtype)
    graph_fn = make_graph_fn(symbol, train=train)
    try:
        closed = jax.make_jaxpr(graph_fn)(
            args, aux, jax.random.PRNGKey(0))
    except Exception:
        return None
    return closed, args, aux


def analyze_symbol(symbol, shapes, type_dict=None, train=False,
                   host_names=None):
    """CostReport for a Symbol's forward program at concrete ``shapes``.

    ``shapes`` must make the graph fully inferable (same contract as the
    GRF006 trace).  ``host_names``: argument names fed from the host each
    call (default: exactly the names in ``shapes`` — data/label; derived
    parameter arguments are device-resident).  Returns None when the
    graph is underspecified or does not trace.
    """
    traced = symbol_closed_jaxpr(symbol, shapes, type_dict=type_dict,
                                 train=train)
    if traced is None:
        return None
    closed, args, aux = traced
    # flat invar order follows the pytree flattening of (args, aux, key):
    # classify host-fed leaves by arg-dict key order (sorted by jax)
    known = {k for k, v in (shapes or {}).items() if v is not None}
    host = set(host_names if host_names is not None else known)
    flat_names = sorted(args) + sorted(aux)
    host_idx = [i for i, name in enumerate(flat_names) if name in host]
    return analyze_jaxpr(closed, host_invars=host_idx,
                         fetched_outvars=range(
                             len(closed.jaxpr.outvars)
                             - len(aux)))


def unpriced_findings(report_or_tape, subject="<program>", disable=()):
    """COST004 findings for every collective the model could not price.

    A ``ppermute`` traced without its axis declared (or a collective
    primitive this module has no formula for) contributes ZERO modeled
    wire bytes — a budget gate built on that number would pass a PR that
    floods the interconnect.  The fallback therefore *names* the hole.
    """
    from .findings import Finding, filter_findings

    rows = getattr(report_or_tape, "unpriced_collectives", None)
    if rows is None:
        rows = getattr(report_or_tape, "unpriced", [])
    findings = []
    for prim, axis, reason in sorted(set(tuple(r) for r in rows)):
        findings.append(Finding(
            "COST004", subject,
            "collective %r over axis %r contributes zero modeled wire "
            "bytes (%s): declare the axis size (axis_env / mesh) or "
            "teach analysis/cost.py its ring formula — an unpriced "
            "collective makes every collective-byte budget a lie"
            % (prim, axis, reason)))
    kernels = getattr(report_or_tape, "unpriced_kernels", [])
    for kname in sorted(set(kernels)):
        findings.append(Finding(
            "COST005", subject,
            "pallas_call kernel %r declares no cost model: its body is "
            "costed once (not once per grid step) and its dataflow is "
            "severed behind a zero-cost connector — register a "
            "declare_kernel_cost(%r) model (analysis/cost.py) so the "
            "budget gate prices it" % (kname, kname)))
    return filter_findings(findings, disable)
