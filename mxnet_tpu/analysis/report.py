"""Reporters: render Findings as text or JSON.

The JSON schema is stable tooling surface (documented in
docs/analysis.md): ``{"version": 1, "schema_version": 3, "findings":
[{"rule", "severity", "subject", "message"}], "counts": {severity: n}}``
plus, when the cost/dist passes ran, a ``"cost"`` section ({target:
CostReport.as_dict()}), a ``"dist"`` section
(:func:`~mxnet_tpu.analysis.dist_lint.dist_summary`) and — schema 3 —
a ``"shard"`` section (:func:`~mxnet_tpu.analysis.shard_prop.
shard_summary`: per-model collective schedules, reshards and the ZeRO
extras).  ``version`` is the original findings-list schema (kept for
pre-cost consumers); ``schema_version`` is bumped when any section's
shape changes — consumers (``tools/parse_log.py``) must refuse newer.
"""
from __future__ import annotations

import json
from collections import Counter

from .findings import ERROR, WARNING, severity_rank

__all__ = ["render_text", "render_json", "worst_severity", "exit_code",
           "SCHEMA_VERSION"]

# bumped in PR 4 (cost/dist sections + the field itself); 3 adds the
# shard section (mxshard collective schedules) and the
# unpriced_collectives row inside each cost report; 4 adds the fusion
# section (mxfuse chain rankings) and the unpriced_kernels row; 5 adds
# the race section (mxrace lock inventory/guards/edges/hierarchy);
# 6 adds the codegen section (mxgen lowered plans per shipped chain)
SCHEMA_VERSION = 6


def _sorted(findings):
    return sorted(findings, key=lambda f: (-severity_rank(f.severity),
                                           f.rule_id, f.subject))


def render_text(findings, title="mxlint"):
    if not findings:
        return "%s: clean (0 findings)" % title
    lines = ["%s: %d finding(s)" % (title, len(findings))]
    lines += ["  %s" % f for f in _sorted(findings)]
    counts = Counter(f.severity for f in findings)
    lines.append("  -- %s" % ", ".join(
        "%d %s" % (counts[s], s) for s in (ERROR, WARNING, "info")
        if counts[s]))
    return "\n".join(lines)


def render_json(findings, cost=None, dist=None, shard=None, fusion=None,
                race=None, codegen=None):
    """``cost``: {target_name: CostReport-or-dict}; ``dist``: the
    dist_summary dict; ``shard``: the shard_summary dict; ``fusion``:
    {target_name: FusionReport-or-dict} (schema 4); ``race``: the
    race_summary dict (schema 5); ``codegen``: the mxgen lowered-plan
    list (schema 6).  Sections appear only when provided."""
    counts = Counter(f.severity for f in findings)
    payload = {
        "version": 1,
        "schema_version": SCHEMA_VERSION,
        "findings": [f.as_dict() for f in _sorted(findings)],
        "counts": dict(counts),
    }
    if cost is not None:
        payload["cost"] = {
            name: (rep.as_dict() if hasattr(rep, "as_dict") else rep)
            for name, rep in sorted(cost.items())}
    if dist is not None:
        payload["dist"] = dist
    if shard is not None:
        payload["shard"] = shard
    if fusion is not None:
        payload["fusion"] = {
            name: (rep.as_dict() if hasattr(rep, "as_dict") else rep)
            for name, rep in sorted(fusion.items())}
    if race is not None:
        payload["race"] = race
    if codegen is not None:
        payload["codegen"] = codegen
    return json.dumps(payload, indent=2)


def worst_severity(findings):
    if not findings:
        return None
    return max((f.severity for f in findings), key=severity_rank)


def exit_code(findings, strict=False):
    """2 on errors, 1 on warnings when strict (self-check/CI), else 0."""
    worst = worst_severity(findings)
    if worst == ERROR:
        return 2
    if worst == WARNING and strict:
        return 1
    return 0
