"""Reporters: render Findings as text or JSON.

The JSON schema is stable tooling surface (documented in
docs/analysis.md): ``{"version": 1, "findings": [{"rule", "severity",
"subject", "message"}], "counts": {severity: n}}``.
"""
from __future__ import annotations

import json
from collections import Counter

from .findings import ERROR, WARNING, severity_rank

__all__ = ["render_text", "render_json", "worst_severity", "exit_code"]


def _sorted(findings):
    return sorted(findings, key=lambda f: (-severity_rank(f.severity),
                                           f.rule_id, f.subject))


def render_text(findings, title="mxlint"):
    if not findings:
        return "%s: clean (0 findings)" % title
    lines = ["%s: %d finding(s)" % (title, len(findings))]
    lines += ["  %s" % f for f in _sorted(findings)]
    counts = Counter(f.severity for f in findings)
    lines.append("  -- %s" % ", ".join(
        "%d %s" % (counts[s], s) for s in (ERROR, WARNING, "info")
        if counts[s]))
    return "\n".join(lines)


def render_json(findings):
    counts = Counter(f.severity for f in findings)
    return json.dumps({
        "version": 1,
        "findings": [f.as_dict() for f in _sorted(findings)],
        "counts": dict(counts),
    }, indent=2)


def worst_severity(findings):
    if not findings:
        return None
    return max((f.severity for f in findings), key=severity_rank)


def exit_code(findings, strict=False):
    """2 on errors, 1 on warnings when strict (self-check/CI), else 0."""
    worst = worst_severity(findings)
    if worst == ERROR:
        return 2
    if worst == WARNING and strict:
        return 1
    return 0
