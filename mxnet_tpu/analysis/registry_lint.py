"""Registry lint: static consistency checks over every registered Op.

Reference: nnvm asserts these invariants at registration time (op.cc) or
lets them explode inside ``jax.jit`` here — a wrong ``arg_names`` order
produces silently-transposed operands, an out-of-range ``aux`` index
corrupts the executor's input packing, and a partial ``num_outputs``
callable kills ``Symbol.list_outputs``.  This pass proves them all before
any trace runs.
"""
from __future__ import annotations

import inspect

from ..ops import registry as _reg
from .findings import Finding, suppressed_rules, filter_findings

__all__ = ["lint_registry", "unique_ops"]


def unique_ops(registry=None):
    """{canonical_name: Op} over unique implementations (aliases folded)."""
    registry = registry or _reg
    seen = {}
    for name in registry.list_ops():
        op = registry.get(name)
        if id(op) not in seen:
            seen[id(op)] = op
    return {op.name: op for op in seen.values()}


def _fn_signature(fn):
    """(positional names, keyword-accepted names, has *args, has **kw) or
    None when introspection fails even through partial/wrapped chains."""
    for candidate in (fn, getattr(fn, "func", None),
                      getattr(fn, "__wrapped__", None)):
        if candidate is None:
            continue
        try:
            sig = inspect.signature(candidate)
        except (TypeError, ValueError):
            continue
        params = list(sig.parameters.values())
        pos = [p.name for p in params
               if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)]
        kw = {p.name for p in params
              if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)}
        has_var = any(p.kind == p.VAR_POSITIONAL for p in params)
        has_kw = any(p.kind == p.VAR_KEYWORD for p in params)
        return pos, kw, has_var, has_kw
    return None


def _fn_defaults(fn):
    """Keyword defaults of fn — the 'registered defaults' that num_outputs/
    optional_args callables must be total over."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return {}
    return {p.name: p.default for p in sig.parameters.values()
            if p.default is not p.empty}


def _lint_op(op):
    out = []
    slots = list(op.arg_names) + [op.aux[k] for k in sorted(op.aux)]
    variadic = op.arg_names == ["args"]
    sig = _fn_signature(op.fn)

    if op.fn_params_fallback or sig is None:
        out.append(Finding("REG011", op.name,
                           "could not introspect fn %r; scalar positional "
                           "args map onto arg_names %r as a guess"
                           % (op.fn, op.arg_names)))

    if sig is not None:
        pos, kw, has_var, has_kw = sig
        if variadic:
            if not has_var:
                out.append(Finding("REG001", op.name,
                                   "variadic op (arg_names=['args']) but fn "
                                   "has no *args parameter"))
        else:
            if not has_var and len(pos) < len(slots):
                out.append(Finding("REG001", op.name,
                                   "fn takes %d positional parameters %r but "
                                   "%d tensor slots are declared %r"
                                   % (len(pos), pos, len(slots), slots)))
            # slot names that fn also uses must keep their relative order —
            # a swap means bound tensors land in transposed parameters
            inter = [n for n in slots if n in pos]
            idx = [pos.index(n) for n in inter]
            if idx != sorted(idx):
                out.append(Finding("REG002", op.name,
                                   "declared slot order %r contradicts fn "
                                   "parameter order %r" % (slots, pos)))
        for s in op.scalar_args:
            if s in slots:
                out.append(Finding("REG003", op.name,
                                   "scalar_args entry %r is also a tensor "
                                   "slot" % (s,)))
            elif s not in kw and not has_kw:
                out.append(Finding("REG003", op.name,
                                   "scalar_args entry %r is not a keyword "
                                   "parameter of fn (params: %r)"
                                   % (s, sorted(kw))))

    defaults = _fn_defaults(op.fn)
    if callable(op.optional_args):
        try:
            opt = set(op.optional_args(defaults))
        except Exception as e:
            opt = None
            out.append(Finding("REG004", op.name,
                               "optional_args callable raised %s: %s over "
                               "registered defaults" % (type(e).__name__, e)))
    else:
        opt = set(op.optional_args)
    if opt and not variadic:
        bad = sorted(opt - set(slots))
        if bad:
            out.append(Finding("REG004", op.name,
                               "optional_args %r name no declared tensor "
                               "slot %r" % (bad, slots)))

    aux_keys = sorted(op.aux)
    want = list(range(len(op.arg_names), len(op.arg_names) + len(op.aux)))
    if aux_keys and aux_keys != want:
        out.append(Finding("REG005", op.name,
                           "aux indices %r must be the contiguous range %r "
                           "after arg_names (input packing order)"
                           % (aux_keys, want)))

    try:
        n_out = op.n_outputs(defaults)
        if not isinstance(n_out, int) or n_out < 1:
            out.append(Finding("REG007", op.name,
                               "num_outputs(defaults) returned %r, expected "
                               "a positive int" % (n_out,)))
            n_out = 1
    except Exception as e:
        out.append(Finding("REG007", op.name,
                           "num_outputs callable raised %s: %s over "
                           "registered defaults" % (type(e).__name__, e)))
        n_out = 1

    # mutated fn outputs sit after the public ones (see Op docstring)
    total_outs = n_out + len(op.mutates)
    for in_idx, out_idx in op.mutates.items():
        if not 0 <= in_idx < len(slots):
            out.append(Finding("REG006", op.name,
                               "mutates input index %d out of range for %d "
                               "tensor slots" % (in_idx, len(slots))))
        if not 0 <= out_idx < total_outs:
            out.append(Finding("REG006", op.name,
                               "mutates fn-output index %d out of range "
                               "(%d public + %d mutated outputs)"
                               % (out_idx, n_out, len(op.mutates))))

    if not op.doc.strip():
        out.append(Finding("REG009", op.name, "op has no docstring"))
    return out


def lint_registry(registry=None, coverage_map=None, disable=()):
    """Run every registry rule over every unique op.

    ``coverage_map``: {op_name: description} enabling REG010 (pass
    ``mxnet_tpu.analysis.coverage.load_test_map()``); None skips the rule.
    """
    registry = registry or _reg
    findings = []
    for name, op in sorted(unique_ops(registry).items()):
        per_op = _lint_op(op)
        if coverage_map is not None:
            from .coverage import lookup
            if lookup(coverage_map, op, registry) is None:
                per_op.append(Finding("REG010", op.name,
                                      "no sweep case or dedicated test "
                                      "file claims this op"))
        muted = suppressed_rules(op.fn)
        findings.extend(f for f in per_op if f.rule_id not in muted)
    for name, old, new in getattr(registry, "shadowed", lambda: [])():
        findings.append(Finding("REG008", name,
                                "registration of %r overwrote %r already "
                                "bound to this name" % (new, old)))
    return filter_findings(findings, disable)
