"""DST rules: static checks over the distributed training step.

The automatic cross-replica sharding literature (PAPERS.md) treats the
weight-update as the property worth proving: every trainable parameter's
gradient must cross the data axis **exactly once** or replicas silently
diverge (missing reduction) or train with K-scaled gradients (duplicate
``psum``).  Under ``jax.jit`` + ``NamedSharding`` the reduction is
compiler-inserted, so nothing in the executed program is inspectable
before launch; the checkable surface is the *per-replica spelling* of
the step — the same computation with the collective written out
(``DataParallelTrainer._build_replica_step``), traced hardware-free via
``jax.make_jaxpr(..., axis_env=[(axis, K)])``.

The core is a variance propagation over the inlined tape
(:mod:`.cost`): program inputs are marked *varying* (different value on
every replica: the batch shard) or *invariant* (identical everywhere:
replicated params, optimizer state, the step's rng key, lr, t).  Any op
with a varying operand produces varying outputs; ``psum``/``pmean``
over the data axis makes its output invariant.  Then:

- **DST001** (error): a new-parameter output is still varying — its
  gradient was never reduced over the data axis; replicas desync.
- **DST002** (warning): a ``psum`` over the axis whose operand is
  already invariant — a duplicate reduction (``psum`` multiplies by K;
  a ``pmean`` spelled through it is a dead collective).
- **DST003** (error): ``NamedSharding`` mismatches between the mesh
  helpers and the step inputs — a parameter PartitionSpec that uses the
  data axis, names an axis the mesh lacks, or outranks the parameter;
  a batch axis the mesh cannot split evenly.
- **DST004**: collective reduction dtype.  A sub-f32 float (bf16/f16)
  reduced over the data axis is an **error** — a ring reduction
  accumulates one rounding per hop, so gradients must be cast to f32
  BEFORE the collective (the mixed-precision contract,
  docs/precision.md; ``precision.PRECISION_F32_GRAD_REDUCE`` is the
  seam proving this gate bites).  An operand already ≥f32 that was
  *widened* right before the collective (f32→f64) stays a warning:
  wider wire bytes than the math needs.
- **DST005** (warning): a Python value was baked into the step program
  as a closure constant.  A step program should be constant-free
  (everything iteration-dependent enters as an argument); a baked value
  traced at different times on different hosts is a cross-host
  divergence hazard (and a retrace trap).
"""
from __future__ import annotations

import numpy as _np

from .cost import build_tape, _aval_bytes
from .findings import ERROR, Finding, filter_findings

__all__ = ["lint_dist_step", "lint_trainer", "dist_summary"]

# collectives that make their output invariant over the reduced axes
_REDUCING = frozenset({"psum", "pmax", "pmin"})
# collectives that touch the axis without establishing invariance
_NON_REDUCING = frozenset({"all_gather", "ppermute", "all_to_all",
                           "reduce_scatter", "pbroadcast"})


def _is_float(dtype):
    import jax.numpy as jnp
    try:
        # jnp.issubdtype knows the extended float lattice (bfloat16,
        # float8_*) that numpy's own issubdtype rejects
        return bool(jnp.issubdtype(jnp.dtype(dtype), jnp.floating))
    except TypeError:
        return False


def _dtype_findings(op, tape, producer, data_axis, subject):
    """DST004 over one reducing collective's operands (module
    docstring): sub-f32 float on the wire is an ERROR, a ≥f32 operand
    widened immediately before the collective stays a WARNING."""
    out = []
    for i in op.in_ids:
        dt = tape.avals[i].dtype
        if not _is_float(dt):
            continue
        if _np.dtype(dt).itemsize < 4:
            out.append(Finding(
                "DST004", subject,
                "%s over axis %r reduces %s on the wire: a ring "
                "reduction accumulates one rounding per hop, so "
                "gradients must be cast to float32 BEFORE the "
                "collective and only narrowed after (the "
                "mixed-precision contract, docs/precision.md)"
                % (op.prim, data_axis, _np.dtype(dt).name),
                severity=ERROR))
            continue
        src = producer.get(i)
        if src is not None and src.prim == "convert_element_type":
            in_dt = tape.avals[src.in_ids[0]].dtype \
                if src.in_ids else dt
            if (_is_float(in_dt)
                    and 4 <= _np.dtype(in_dt).itemsize
                    < _np.dtype(dt).itemsize):
                out.append(Finding(
                    "DST004", subject,
                    "%s over axis %r reduces a value widened "
                    "%s->%s immediately before the collective: "
                    "%.2f MiB on the wire where %.2f would do — "
                    "reduce in %s and widen after (or make the "
                    "promotion explicit)"
                    % (op.prim, data_axis, _np.dtype(in_dt).name,
                       _np.dtype(dt).name,
                       _aval_bytes(tape.avals[i]) / (1 << 20),
                       _aval_bytes(tape.avals[src.in_ids[0]])
                       / (1 << 20), _np.dtype(in_dt).name)))
    return out


def lint_dist_step(closed_jaxpr, data_axis, varying_invars,
                   param_outvars=None, param_names=None, axis_size=None,
                   disable=(), subject="<step>"):
    """Run DST001/002/004/005 over a traced step.

    ``varying_invars``: flat invar indices holding per-replica values
    (the batch shard).  ``param_outvars``: flat outvar indices that are
    the *new parameter values* (checked invariant); default: every
    outvar.  ``param_names``: display names aligned with
    ``param_outvars``.
    """
    tape = build_tape(closed_jaxpr,
                      axis_sizes={data_axis: axis_size or 1})
    varying = set()
    for i in varying_invars:
        if 0 <= i < len(tape.invar_ids):
            varying.add(tape.invar_ids[i])

    findings = []
    producer = {}
    for op in tape.ops:
        for o in op.out_ids:
            producer[o] = op
        touches_axis = data_axis in op.axes
        any_varying = any(i in varying for i in op.in_ids)
        if op.prim in _REDUCING and touches_axis:
            if not any_varying:
                findings.append(Finding(
                    "DST002", subject,
                    "%s over axis %r applied to a value already invariant "
                    "over it — a duplicate reduction: psum multiplies by "
                    "the axis size, pmean is a dead collective"
                    % (op.prim, data_axis)))
            # reduced over the data axis: output identical on every
            # replica regardless of operand variance
            findings.extend(_dtype_findings(op, tape, producer,
                                            data_axis, subject))
            continue
        if op.prim in _NON_REDUCING and touches_axis:
            if op.prim == "reduce_scatter":
                # a reduce_scatter sums over the wire exactly like psum
                # (only the result layout differs): same dtype contract
                findings.extend(_dtype_findings(op, tape, producer,
                                                data_axis, subject))
            # value still differs per replica (gathered/permuted layout)
            if any_varying:
                varying.update(op.out_ids)
            continue
        if any_varying:
            varying.update(op.out_ids)

    out_idx = (range(len(tape.outvar_ids)) if param_outvars is None
               else param_outvars)
    names = list(param_names or [])
    for j, oi in enumerate(out_idx):
        if not (0 <= oi < len(tape.outvar_ids)):
            continue
        if tape.outvar_ids[oi] in varying:
            name = names[j] if j < len(names) else "output %d" % oi
            findings.append(Finding(
                "DST001", name,
                "new value of %r still varies over mesh axis %r: its "
                "gradient is never psum/pmean-reduced over the data "
                "axis, so replicas silently diverge after one step"
                % (name, data_axis)))

    for i in tape.const_ids:
        aval = tape.avals[i]
        findings.append(Finding(
            "DST005", subject,
            "step program closes over a baked constant %s%s (%d bytes): "
            "iteration-dependent Python values captured at trace time "
            "diverge across hosts that trace at different moments — "
            "pass it as an argument instead"
            % (getattr(aval, "dtype", "?"),
               tuple(getattr(aval, "shape", ())), _aval_bytes(aval))))
    return filter_findings(findings, disable)


def _check_shardings(mesh, data_axis, param_specs, batch_dims,
                     disable=(), subject="<trainer>"):
    """DST003: mesh/PartitionSpec consistency between the mesh helpers
    and the step inputs."""
    findings = []
    axis_names = tuple(mesh.axis_names)
    axis_sizes = dict(zip(axis_names, mesh.devices.shape))
    if data_axis not in axis_names:
        findings.append(Finding(
            "DST003", subject,
            "data axis %r is not an axis of the mesh %r — the batch "
            "cannot be sharded and the gradient reduction has no axis "
            "to ride" % (data_axis, axis_names)))
        return filter_findings(findings, disable)
    for name, (shape, spec) in sorted(param_specs.items()):
        spec_axes = [a for part in tuple(spec) if part is not None
                     for a in ((part,) if isinstance(part, str)
                               else tuple(part))]
        if len(tuple(spec)) > len(shape):
            findings.append(Finding(
                "DST003", name,
                "PartitionSpec %r has %d entries but parameter %r is "
                "rank %d" % (tuple(spec), len(tuple(spec)), name,
                             len(shape))))
            continue
        for a in spec_axes:
            if a not in axis_names:
                findings.append(Finding(
                    "DST003", name,
                    "PartitionSpec %r names axis %r which the mesh %r "
                    "does not have" % (tuple(spec), a, axis_names)))
        if data_axis in spec_axes:
            findings.append(Finding(
                "DST003", name,
                "parameter %r is sharded over the data axis %r: the "
                "data axis carries the batch and the gradient psum — a "
                "weight laid out over it desyncs with the replicated "
                "update (use a model/tensor axis for weight sharding)"
                % (name, data_axis)))
        for dim, a in zip(shape, tuple(spec)):
            for ax in ((a,) if isinstance(a, str) else tuple(a or ())):
                sz = axis_sizes.get(ax)
                if sz and int(dim) % int(sz) != 0:
                    findings.append(Finding(
                        "DST003", name,
                        "dim %d of %r is not divisible by axis %r "
                        "(size %d)" % (int(dim), name, ax, int(sz))))
    ksize = int(axis_sizes[data_axis])
    for name, dim in sorted(batch_dims.items()):
        if int(dim) % ksize != 0:
            findings.append(Finding(
                "DST003", name,
                "batch input %r has leading dim %d, not divisible by "
                "data axis %r (size %d) — NamedSharding placement "
                "fails at step time" % (name, int(dim), data_axis,
                                        ksize)))
    return filter_findings(findings, disable)


def lint_trainer(trainer, data_shape=None, label_shape=None,
                 data_dtype="float32", label_dtype="int32",
                 declared_axis_size=None, disable=()):
    """Full DST pass over a ``DataParallelTrainer``.

    Traces the trainer's per-replica step (explicit collectives) with
    ``make_jaxpr(axis_env=...)`` — no devices beyond the trainer's own
    mesh are needed — and combines the jaxpr rules with the DST003
    sharding-consistency checks.  ``data_shape``/``label_shape`` are
    required if the trainer has not seen a batch yet.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import _rng
    from ..ndarray import NDArray

    if not trainer._ready:
        if data_shape is None:
            raise ValueError(
                "trainer has not stepped yet: pass data_shape (and "
                "label_shape) so the step can be traced")
        x0 = NDArray(jnp.zeros(tuple(data_shape), _np.dtype(data_dtype)))
        y0 = NDArray(jnp.zeros(tuple(label_shape or (data_shape[0],)),
                               _np.dtype(label_dtype)))
        trainer._setup(x0, y0)
        data_shape = tuple(data_shape)
        label_shape = tuple(label_shape or (data_shape[0],))
    else:
        if data_shape is None or label_shape is None:
            raise ValueError("pass the step's data_shape/label_shape")
        data_shape = tuple(data_shape)
        label_shape = tuple(label_shape)

    mesh = trainer._mesh
    axis = trainer._data_axis
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    ksize = int(declared_axis_size or axis_sizes.get(axis, 1))

    param_specs = {
        name: (tuple(p.shape),
               trainer._param_spec_fn(name, p.shape))
        for name, p in trainer._params_by_name.items()
        if p.grad_req != "null"}
    findings = _check_shardings(
        mesh, axis, param_specs,
        {"data": data_shape[0], "label": label_shape[0]},
        disable=disable, subject="DataParallelTrainer")

    # the per-replica spelling sees the batch SHARD
    shard = max(data_shape[0] // max(ksize, 1), 1)
    x = jax.ShapeDtypeStruct((shard,) + data_shape[1:],
                             _np.dtype(data_dtype))
    y = jax.ShapeDtypeStruct((shard,) + label_shape[1:],
                             _np.dtype(label_dtype))
    train_vals = tuple(trainer._params_by_name[n].data()._data
                       for n in trainer._train_names)
    aux_vals = tuple(trainer._params_by_name[n].data()._data
                     for n in trainer._aux_names)
    states = tuple(trainer._states_raw)
    key = jax.ShapeDtypeStruct((2,), _np.dtype(np.uint32))
    step = trainer._build_replica_step()
    try:
        closed = jax.make_jaxpr(step, axis_env=[(axis, ksize)])(
            train_vals, states, aux_vals, x, y, key,
            jnp.float32(0.01), jnp.int32(1))
    except Exception as e:
        findings.append(Finding(
            "DST001", "DataParallelTrainer",
            "per-replica step does not trace (%s: %s) — the distributed "
            "step cannot be verified statically"
            % (type(e).__name__, str(e)[:200])))
        return filter_findings(findings, disable)

    # flat layout of the step args: train_vals, states, aux, x, y, key,
    # lr, t — only the batch (x, y) varies per replica
    n_train = len(jax.tree_util.tree_leaves(train_vals))
    n_states = len(jax.tree_util.tree_leaves(states))
    n_aux = len(jax.tree_util.tree_leaves(aux_vals))
    varying = [n_train + n_states + n_aux,
               n_train + n_states + n_aux + 1]
    # outputs: loss, new_vals..., new_states..., muts... — the new
    # parameter values are outvars [1, 1 + n_train)
    param_out = list(range(1, 1 + n_train))
    findings += lint_dist_step(
        closed, axis, varying_invars=varying, param_outvars=param_out,
        param_names=list(trainer._train_names), axis_size=ksize,
        disable=disable, subject="DataParallelTrainer")
    # the loss every rank reports must also be the global (invariant)
    # mean — checked as a pseudo-parameter
    findings += [
        Finding("DST001", "loss",
                f.message.replace("gradient", "value"))
        for f in lint_dist_step(
            closed, axis, varying_invars=varying, param_outvars=[0],
            param_names=["loss"], axis_size=ksize, disable=("DST002",
                                                            "DST004",
                                                            "DST005"))
        if f.rule_id == "DST001"]
    return filter_findings(findings, disable)


def dist_summary(findings, axis_sizes=None, params_checked=0):
    """Machine-readable ``dist`` section for the CLI ``--json`` output."""
    return {
        "rules": ["DST001", "DST002", "DST003", "DST004", "DST005",
                  "DST006", "DST007", "DST008", "DST009", "DST010",
                  "DST011", "DST012"],
        "axis_sizes": {k: int(v)
                       for k, v in sorted((axis_sizes or {}).items())},
        "params_checked": int(params_checked),
        "findings": [f.as_dict() for f in findings
                     if f.rule_id.startswith("DST")],
    }
