"""Source lint: AST checks over user scripts for trace-time traps.

A Symbol graph is static, but the python driving it is not: pulling a
scalar out of an array (``.item()``, ``.asscalar()``, ``int(x)``) blocks
on the device and bakes the value into the next trace, and branching on a
runtime ``.shape`` retraces the jit cache per input geometry — the exact
recompile bugs ``jax.jit`` only reveals as slowness.  SRC004 is the
dispatch-side companion: a blocking host fetch that runs once per
dispatched training step (``float(loss)``, ``.asscalar()``,
``np.asarray``) collapses the engine's run-ahead window to 1 — the loop
is then input-bound no matter how fast the device is.  The rule fires
only when the sync's *innermost* enclosing loop also dispatches steps
(``.step()``/``forward_backward``/``backward``/``update``), so
epoch-boundary fetches and periodic ``if step % k == 0`` flush guards
stay clean.  These rules are heuristic (python is dynamic); they point
at lines worth reading, they do not prove bugs.
"""
from __future__ import annotations

import ast

from .findings import Finding, filter_findings

__all__ = ["lint_source", "lint_file"]

# method calls that materialize device data into python scalars
_SYNC_METHODS = {"item", "asscalar", "asnumpy", "tolist"}
# builtins that, applied to array expressions, capture a python scalar
_CAST_BUILTINS = {"int", "float", "bool"}
# additional device->host materializers for the training-loop rule
# (SRC004): these don't bake values into traces (SRC001's concern) but
# they DO block the host on the device every step
_SYNC_EXTRA = {"wait_to_read", "block_until_ready"}
# np.asarray(<expr>) materializes the device value on the host; plain
# nd.array/np.array *construction* from host data is h2d, not a sync,
# so only asarray participates
_HOST_FETCH_FUNCS = {"asarray"}
# calls that mark a loop as a *training* loop: the sync then runs at
# step frequency, which is exactly the anti-pattern (SRC004)
_STEP_CALLS = {"step", "forward_backward", "backward", "update"}
# unbounded blocking receivers (SRC005): zero-arg, no timeout= — inside a
# while-style worker/heartbeat loop these wedge forever when the peer
# (queue writer, socket, thread) dies.  Calls with any positional arg are
# excluded by construction (sock.recv(n), " ".join(xs), q.get(timeout))
_BLOCKING_CALLS = {"get", "recv", "wait", "join"}
# host-side normalization entry points (SRC003): the device tail does the
# same math fused into the first jitted step, off the host's critical path
_NORMALIZE_CALLS = {"color_normalize", "ColorNormalizeAug"}
# iterator factories where mean/std kwargs without device_tail=True pin the
# normalize (and a float32 transfer) onto the host
_ITER_FACTORIES = {"ImageRecordIter", "ImageIter", "CreateAugmenter"}
_MEANSTD_KWARGS = {"mean", "std", "mean_r", "mean_g", "mean_b",
                   "std_r", "std_g", "std_b"}


def _contains_shape(node):
    return any(isinstance(sub, ast.Attribute) and sub.attr in ("shape", "size",
                                                               "ndim")
               for sub in ast.walk(node))


def _is_arrayish(node):
    """Conservative guess that an expression produces array data: a call
    result, subscript, or attribute chain — not a bare literal/name."""
    return isinstance(node, (ast.Call, ast.Subscript, ast.Attribute,
                             ast.BinOp))


def _mentions(node, word):
    """Any identifier/attribute under ``node`` whose name contains
    ``word`` (case-insensitive) — e.g. ``rgb_mean``, ``cfg.std``."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None and word in name.lower():
            return True
    return False


def _call_name(fn):
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class _LoopFrame:
    """Per-loop bookkeeping for SRC004: syncs whose *innermost* enclosing
    loop is this one, and whether this loop directly (same innermost
    level) dispatches training steps.  A sync only fires when both hold —
    i.e. it runs at the same frequency as the step dispatch; an
    epoch-boundary fetch (innermost loop = the epoch loop, steps live in
    the nested batch loop) stays clean.  ``kind`` ('while'/'for') also
    scopes SRC005 to while-style worker loops."""

    __slots__ = ("syncs", "has_step", "kind")

    def __init__(self, kind="for"):
        self.syncs = []      # (node, description)
        self.has_step = False
        self.kind = kind


class _Visitor(ast.NodeVisitor):
    def __init__(self, filename):
        self.filename = filename
        self.findings = []
        self.suppressed = {}   # lineno -> set(rule_ids), filled by caller
        self._loops = []       # _LoopFrame stack (innermost last)
        self._flush_guard = 0  # depth of `if step % k == 0`-style guards

    def _emit(self, rule, node, msg):
        muted = self.suppressed.get(node.lineno, ())
        if rule not in muted:
            self.findings.append(Finding(
                rule, "%s:%d" % (self.filename, node.lineno), msg))

    # -- SRC004 scaffolding ------------------------------------------------
    def _visit_loop(self, node, kind):
        self._check_branch(node, kind)
        self._loops.append(_LoopFrame(kind="while"))
        self.generic_visit(node)
        self._flush_loop_frame()

    def _flush_loop_frame(self):
        frame = self._loops.pop()
        if frame.has_step:
            for sync_node, what in frame.syncs:
                self._emit("SRC004", sync_node,
                           "%s runs once per dispatched training step: it "
                           "blocks the host on the device and collapses "
                           "the engine's run-ahead window to 1; accumulate "
                           "on device / metric.update_lazy and fetch at a "
                           "flush boundary (epoch end, or an `if step %% k "
                           "== 0` guard)" % what)

    def _note_sync(self, node, what):
        if self._loops and not self._flush_guard:
            self._loops[-1].syncs.append((node, what))

    def visit_FunctionDef(self, node):
        # a nested def is a new runtime scope: its body does not execute
        # per iteration of the enclosing loop
        outer_loops, outer_guard = self._loops, self._flush_guard
        self._loops, self._flush_guard = [], 0
        self.generic_visit(node)
        self._loops, self._flush_guard = outer_loops, outer_guard

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_Call(self, node):
        fn = node.func
        name = _call_name(fn)
        if self._loops and name in _STEP_CALLS:
            self._loops[-1].has_step = True
        # SRC005: zero-arg blocking receiver whose innermost enclosing
        # loop is while-style (the worker/heartbeat-loop shape).  Any
        # positional arg or a timeout=/block= kwarg bounds the wait.
        if isinstance(fn, ast.Attribute) and fn.attr in _BLOCKING_CALLS \
                and not node.args \
                and not any(k.arg in ("timeout", "block")
                            for k in node.keywords) \
                and self._loops and self._loops[-1].kind == "while":
            self._emit("SRC005", node,
                       ".%s() with no timeout inside a while-loop: a "
                       "dead peer (killed worker process, closed socket, "
                       "wedged thread) blocks this loop forever; use "
                       ".%s(timeout=...) and re-check liveness/stop "
                       "conditions on each wake" % (fn.attr, fn.attr))
        if isinstance(fn, ast.Attribute) and \
                fn.attr in (_SYNC_METHODS | _SYNC_EXTRA):
            self._note_sync(node, ".%s()" % fn.attr)
        elif isinstance(fn, ast.Name) and fn.id in _CAST_BUILTINS and \
                node.args and _is_arrayish(node.args[0]) and \
                not _contains_shape(node.args[0]):
            self._note_sync(node, "%s(...) of an array" % fn.id)
        elif isinstance(fn, ast.Attribute) and \
                fn.attr in _HOST_FETCH_FUNCS and node.args and \
                _is_arrayish(node.args[0]):
            self._note_sync(node, ".%s(...) of an array" % fn.attr)
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
            self._emit("SRC001", node,
                       ".%s() synchronizes with the device and captures a "
                       "python value; inside a training loop this blocks "
                       "dispatch and can force retraces" % fn.attr)
        elif isinstance(fn, ast.Name) and fn.id in _CAST_BUILTINS and \
                node.args and _is_arrayish(node.args[0]) and \
                not _contains_shape(node.args[0]):
            self._emit("SRC001", node,
                       "%s(...) of an array expression captures a python "
                       "scalar at trace time; the traced graph bakes this "
                       "value in" % fn.id)
        if name in _NORMALIZE_CALLS:
            self._emit("SRC003", node,
                       "%s() normalizes on the host (float math per image "
                       "and a float32-wide transfer); ship uint8 and fuse "
                       "the normalize on device instead "
                       "(ImageRecordIter(device_tail=True) or "
                       "mx.io.make_device_tail)" % name)
        elif name in _ITER_FACTORIES:
            kwargs = {k.arg for k in node.keywords if k.arg}
            if kwargs & _MEANSTD_KWARGS and "device_tail" not in kwargs:
                self._emit("SRC003", node,
                           "%s(mean/std=...) without device_tail=True "
                           "normalizes every batch on the host; pass "
                           "device_tail=True to fuse the mean/std + cast "
                           "+ layout tail into the device step and ship "
                           "raw uint8" % name)
        self.generic_visit(node)

    def visit_BinOp(self, node):
        # `(x - mean) / std` spelled out over arrays: the host-normalize
        # idiom every MXNet driver script inherited
        if isinstance(node.op, ast.Div) and \
                isinstance(node.left, ast.BinOp) and \
                isinstance(node.left.op, ast.Sub) and \
                _mentions(node.left.right, "mean") and \
                _mentions(node.right, "std"):
            self._emit("SRC003", node,
                       "host-side `(x - mean) / std` normalization; the "
                       "fused device tail does this math on device off "
                       "the input pipeline's critical path "
                       "(mx.io.make_device_tail)")
        self.generic_visit(node)

    def _check_branch(self, node, kind):
        if _contains_shape(node.test):
            self._emit("SRC002", node,
                       "%s on a runtime .shape/.size/.ndim: each distinct "
                       "geometry traces a new program; prefer shape codes "
                       "(0/-1) or pad to a fixed bucket" % kind)

    def visit_If(self, node):
        self._check_branch(node, "if-branch")
        # `if step % k == 0:` is the periodic-flush idiom (Speedometer,
        # logging ticks): a sync under it is a flush-boundary fetch, the
        # SRC004 FIX, not the anti-pattern
        periodic = any(isinstance(sub, ast.BinOp)
                       and isinstance(sub.op, ast.Mod)
                       for sub in ast.walk(node.test))
        if periodic:
            self._flush_guard += 1
        self.generic_visit(node)
        if periodic:
            self._flush_guard -= 1

    def visit_While(self, node):
        self._visit_loop(node, "while-loop")

    def visit_For(self, node):
        self._loops.append(_LoopFrame())
        self.generic_visit(node)
        self._flush_loop_frame()

    visit_AsyncFor = visit_For


def _line_suppressions(source):
    """{lineno: rule_ids} for ``# mxlint: disable=...`` trailing comments."""
    from .findings import _DISABLE_RE
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _DISABLE_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def lint_source(source, filename="<string>", disable=()):
    """Lint python source text; returns a list of Findings."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        raise ValueError("cannot parse %s: %s" % (filename, e))
    v = _Visitor(filename)
    v.suppressed = _line_suppressions(source)
    v.visit(tree)
    return filter_findings(v.findings, disable)


def lint_file(path, disable=()):
    with open(path) as f:
        return lint_source(f.read(), filename=path, disable=disable)
