"""mxgen — compile mined fusion chains into generated Pallas kernels.

PR 15's mxfuse *ranks* memory-bound chains by modeled bytes-saved; a
human still wrote every kernel.  This tier closes ROADMAP item 4 the
way TVM closes its fusion loop (PAPERS.md arxiv 1802.04799): the top
chains of the transformer train-step and ZeRO-1 tapes are **lowered
mechanically** from the tape eqns into Pallas kernel source, their
``KERNEL_COSTS`` entry is auto-declared from the chain's modeled
``fused_bytes`` (FUS001 declared-vs-tape parity holds by construction),
and a GEN-rule lint proves every shipped chain stays inside the
provable-lowering set.

The lowering has TWO independent implementations of each primitive's
semantics:

- ``_EMIT``    — prim → kernel-source emitter (what Pallas runs);
- ``_PRIM_EVAL`` — prim → reference interpreter over the original tape
  eqns (what the chain meant).

The auto-equivalence check runs both on the same seeded inputs and
compares at the PR-15 tolerance (1e-5).  Because the paths are
independent, a mislowered eqn (the ``MXGEN_LOWER_EXACT`` mutation seam
flips ``sub`` to ``add`` in the EMITTED source only) diverges and fails
FUS001 through the unmodified STATIC_BUDGETS gate — rc=2, no test
edits.

Block shapes for the flat-tileable (pure elementwise, single 1-D shape
family) kernels come from a seeded host-measured autotune over the
pinned ``AUTOTUNE_LADDER``, cached to disk and replayed bitwise (the
r05 subprocess-bench discipline: a valid cache is never re-measured or
rewritten, so two runs sharing a cache produce byte-identical files).
"""
from __future__ import annotations

import json
import os

from .cost import build_tape
from .findings import Finding, filter_findings
from .fusion import analyze_tape_fusion

__all__ = [
    "LOWERABLE", "MXGEN_LOWER_EXACT", "AUTOTUNE_LADDER", "AUTOTUNE_SEED",
    "LoweredKernel", "chain_externals", "lower_chain", "seeded_inputs",
    "exec_kernel_source", "reference_outputs", "equivalence_check_host",
    "flat_tileable", "autotune_block_rows", "shipped_tape",
    "shipped_lowered", "shipped_chain_rows", "codegen_plans",
    "render_codegen", "lint_generated_kernels",
]

# ---------------------------------------------------------------------------
# mutation seam (tests only): False makes the EMITTER lower every `sub`
# eqn as `add` — the reference interpreter is untouched, so the
# auto-equivalence check diverges and the budget gate fails FUS001
# ---------------------------------------------------------------------------
MXGEN_LOWER_EXACT = True

# the provable-lowering set: every prim mxgen knows how to emit AND
# interpret.  A chain containing anything else is GEN001 (error) — it
# stays a hand-written-kernel candidate instead of silently miscompiling
_ELEMENTWISE_BINOPS = {
    "add": "lax.add", "add_any": "lax.add", "sub": "lax.sub",
    "mul": "lax.mul", "div": "lax.div", "max": "lax.max",
    "min": "lax.min", "pow": "lax.pow", "rem": "lax.rem",
    "gt": "lax.gt", "ge": "lax.ge", "lt": "lax.lt", "le": "lax.le",
    "eq": "lax.eq", "ne": "lax.ne",
    "and": "lax.bitwise_and", "or": "lax.bitwise_or",
    "xor": "lax.bitwise_xor",
}
_ELEMENTWISE_UNOPS = {
    "neg": "lax.neg", "abs": "lax.abs", "sign": "lax.sign",
    "floor": "lax.floor", "ceil": "lax.ceil",
    "exp": "lax.exp", "exp2": "lax.exp2", "log": "lax.log",
    "log1p": "lax.log1p", "tanh": "lax.tanh", "sqrt": "lax.sqrt",
    "rsqrt": "lax.rsqrt", "logistic": "lax.logistic",
    "sin": "lax.sin", "cos": "lax.cos", "erf": "lax.erf",
    "is_finite": "lax.is_finite", "not": "lax.bitwise_not",
}
_REDUCES = {"reduce_sum": "jnp.sum", "reduce_max": "jnp.max",
            "reduce_min": "jnp.min", "reduce_prod": "jnp.prod",
            "reduce_and": "jnp.all", "reduce_or": "jnp.any"}
_IDENTITY = {"copy", "stop_gradient"}
_STRUCTURAL = {"broadcast_in_dim", "convert_element_type", "select_n",
               "integer_pow", "squeeze", "expand_dims"}

LOWERABLE = frozenset(_ELEMENTWISE_BINOPS) | frozenset(_ELEMENTWISE_UNOPS) \
    | frozenset(_REDUCES) | _IDENTITY | _STRUCTURAL

# the pinned autotune candidate ladder: block rows × 128 lanes for the
# flat row-tiled execution path (f32 min tile is (8, 128))
AUTOTUNE_LADDER = (8, 32, 128, 256)
AUTOTUNE_SEED = 20260807
AUTOTUNE_CACHE_SCHEMA = 1
AUTOTUNE_REPS = 3

# the shipped chains: top-3 of each target tape, replacing hand-written
# candidates with zero new hand-written kernels
SHIPPED_TOP_N = 3
SHIPPED_TAPES = ("tp_transformer", "zero1")
EQUIV_TOL = 1e-5        # the PR-15 fused-vs-unfused tolerance
EQUIV_SEED = 0


def _dims(params, key):
    v = params.get(key) or ()
    return tuple(int(d) for d in v)


def _dtype_name(dt):
    import numpy as np
    try:
        return str(np.dtype(dt))
    except TypeError:
        return str(dt)


# ---------------------------------------------------------------------------
# path 1: the emitter — prim → kernel source text
# ---------------------------------------------------------------------------
def _emit_rhs(prim, args, params):
    """RHS source for one tape eqn.  ``args`` are operand source
    expressions (var names or inlined literals), already in eqn order."""
    if prim in _ELEMENTWISE_BINOPS:
        fn = _ELEMENTWISE_BINOPS[prim]
        if not MXGEN_LOWER_EXACT and prim == "sub":
            fn = "lax.add"          # the mislowering seam (tests only)
        return "%s(%s, %s)" % (fn, args[0], args[1])
    if prim in _ELEMENTWISE_UNOPS:
        return "%s(%s)" % (_ELEMENTWISE_UNOPS[prim], args[0])
    if prim in _REDUCES:
        return "%s(%s, axis=%r)" % (_REDUCES[prim], args[0],
                                    _dims(params, "axes"))
    if prim in _IDENTITY:
        return args[0]
    if prim == "integer_pow":
        return "lax.integer_pow(%s, %d)" % (args[0], int(params["y"]))
    if prim == "convert_element_type":
        return "lax.convert_element_type(%s, _dtype(%r))" \
            % (args[0], _dtype_name(params["new_dtype"]))
    if prim == "broadcast_in_dim":
        return "lax.broadcast_in_dim(%s, %r, %r)" \
            % (args[0], tuple(int(d) for d in params["shape"]),
               _dims(params, "broadcast_dimensions"))
    if prim == "select_n":
        return "lax.select_n(%s)" % ", ".join(args)
    if prim == "squeeze":
        return "lax.squeeze(%s, %r)" % (args[0],
                                        _dims(params, "dimensions"))
    if prim == "expand_dims":
        return "lax.expand_dims(%s, %r)" % (args[0],
                                            _dims(params, "dimensions"))
    raise KeyError(prim)


# ---------------------------------------------------------------------------
# path 2: the reference interpreter — prim → callable over arrays.
# Deliberately a SEPARATE implementation (not exec of emitted text): an
# emitter bug diverges here instead of reproducing itself.
# ---------------------------------------------------------------------------
def _prim_eval(prim, invals, params):
    import jax
    import jax.numpy as jnp
    lax = jax.lax

    if prim in ("add", "add_any"):
        return lax.add(invals[0], invals[1])
    if prim in _ELEMENTWISE_BINOPS:
        name = _ELEMENTWISE_BINOPS[prim].split(".", 1)[1]
        return getattr(lax, name)(invals[0], invals[1])
    if prim in _ELEMENTWISE_UNOPS:
        name = _ELEMENTWISE_UNOPS[prim].split(".", 1)[1]
        return getattr(lax, name)(invals[0])
    if prim in _REDUCES:
        name = _REDUCES[prim].split(".", 1)[1]
        return getattr(jnp, name)(invals[0], axis=_dims(params, "axes"))
    if prim in _IDENTITY:
        return invals[0]
    if prim == "integer_pow":
        return lax.integer_pow(invals[0], int(params["y"]))
    if prim == "convert_element_type":
        return lax.convert_element_type(invals[0], params["new_dtype"])
    if prim == "broadcast_in_dim":
        return lax.broadcast_in_dim(
            invals[0], tuple(int(d) for d in params["shape"]),
            _dims(params, "broadcast_dimensions"))
    if prim == "select_n":
        return lax.select_n(invals[0], *invals[1:])
    if prim == "squeeze":
        return lax.squeeze(invals[0], _dims(params, "dimensions"))
    if prim == "expand_dims":
        return lax.expand_dims(invals[0], _dims(params, "dimensions"))
    raise KeyError(prim)


def _literal_src(tape, i):
    """Inline source for a literal operand (value recorded on the tape
    by the cost pass).  Scalars stay weak-typed Python literals — the
    jaxpr spelled them that way; reprs round-trip exactly."""
    import numpy as np

    v = np.asarray(tape.literal_values[i])
    if v.ndim == 0:
        if v.dtype == np.bool_:
            return repr(bool(v))
        if np.issubdtype(v.dtype, np.integer):
            return repr(int(v))
        return repr(float(v))
    return "jnp.asarray(%r, _dtype(%r))" % (v.tolist(),
                                            _dtype_name(v.dtype))


def _literal_val(tape, i):
    import jax.numpy as jnp
    return jnp.asarray(tape.literal_values[i], tape.avals[i].dtype)


def _eqn_avals_consistent(tape, op):
    """True when abstract-evaluating the reference semantics over the
    RECORDED operand avals reproduces the recorded output aval — the
    provability guard against approximate inlining edges (a severed
    scan slice, a pallas ref connector) masquerading as chain dataflow."""
    import jax

    try:
        ins = []
        for i in op.in_ids:
            if i in tape.literal_ids:
                ins.append(_literal_val(tape, i))
            else:
                aval = tape.avals[i]
                ins.append(jax.ShapeDtypeStruct(
                    tuple(aval.shape), aval.dtype))
        out = jax.eval_shape(lambda *a: _prim_eval(op.prim, list(a),
                                                   op.params), *ins)
        want = tape.avals[op.out_ids[0]]
        return (tuple(out.shape) == tuple(want.shape)
                and out.dtype == want.dtype)
    except Exception:  # noqa: BLE001 — any failure to re-infer is a "no"
        return False


def chain_externals(tape, chain):
    """(ext_in ids, ext_out ids) of a chain — the _chain_stats buffer
    sets, in the same sorted order the byte model counts them."""
    idx_set = set(chain.op_indices)
    produced = set()
    for i in chain.op_indices:
        produced.update(tape.ops[i].out_ids)
    ext_in = sorted({iid for i in chain.op_indices
                     for iid in tape.ops[i].in_ids
                     if iid not in produced
                     and iid not in tape.literal_ids})
    prog_outs = set(tape.outvar_ids)
    consumed = set()
    for k, op in enumerate(tape.ops):
        if k in idx_set:
            continue
        for iid in op.in_ids:
            if iid in produced:
                consumed.add(iid)
    ext_out = sorted({oid for oid in produced
                      if oid in consumed or oid in prog_outs})
    return ext_in, ext_out


class LoweredKernel:
    """One chain lowered to Pallas kernel source + its cost contract.

    ``src`` is None when the chain is not provably lowerable — the
    GEN001 findings say why; everything byte-modeled still carries over
    so callers can report the chain either way."""

    __slots__ = ("name", "tag", "rank", "src", "ext_in", "ext_out",
                 "in_avals", "out_avals", "kind", "prims", "n_ops",
                 "scale", "unfused_bytes", "fused_bytes", "bytes_saved",
                 "bytes_read", "bytes_written", "flops",
                 "transcendentals", "findings", "tape", "chain")

    def as_plan(self):
        return {
            "name": self.name,
            "tape": self.tag,
            "rank": int(self.rank),
            "kind": self.kind,
            "n_ops": int(self.n_ops),
            "prims": sorted(set(self.prims)),
            "n_inputs": len(self.ext_in),
            "n_outputs": len(self.ext_out),
            "unfused_bytes": int(self.unfused_bytes),
            "fused_bytes": int(self.fused_bytes),
            "bytes_saved": int(self.bytes_saved),
            "lowerable": self.src is not None,
            "findings": [f.rule_id for f in self.findings],
            "src": self.src,
        }


def lower_chain(tape, chain, name, tag="chain", rank=0):
    """Lower one FusionChain from the tape into a LoweredKernel.

    The emitted body is deterministic in the tape: ops in tape order,
    external buffers in sorted-id order, literals inlined.  Scalar
    ``()`` externals ride as ``(1,)`` buffers (Pallas refs want rank);
    the body reshapes them back."""
    lk = LoweredKernel()
    lk.name = name
    lk.tag = tag
    lk.rank = rank
    lk.kind = chain.kind
    lk.prims = list(chain.prims)
    lk.n_ops = len(chain.op_indices)
    lk.scale = int(chain.scale) or 1
    lk.unfused_bytes = int(chain.unfused_bytes)
    lk.fused_bytes = int(chain.fused_bytes)
    lk.bytes_saved = int(chain.bytes_saved)
    lk.tape = tape
    lk.chain = chain
    lk.findings = []

    ops = [tape.ops[i] for i in chain.op_indices]
    for idx, op in zip(chain.op_indices, ops):
        if op.prim in LOWERABLE and len(op.out_ids) == 1 \
                and not _eqn_avals_consistent(tape, op):
            lk.findings.append(Finding(
                "GEN001", "%s#%d" % (name, chain.first_op),
                "chain eqn %d (%r) has tape dataflow the lowering "
                "cannot prove: the recorded operand/result avals do "
                "not re-infer (an approximate inlining edge) — the "
                "chain stays a hand-written-kernel candidate"
                % (idx, op.prim)))
    for op in ops:
        if op.prim not in LOWERABLE:
            lk.findings.append(Finding(
                "GEN001", "%s#%d" % (name, chain.first_op),
                "chain op %r (eqn %d) is outside the provable-lowering "
                "set — mxgen refuses to guess its semantics; the chain "
                "stays a hand-written-kernel candidate"
                % (op.prim, chain.op_indices[ops.index(op)])))
        elif len(op.out_ids) != 1:
            lk.findings.append(Finding(
                "GEN001", "%s#%d" % (name, chain.first_op),
                "chain op %r has %d outputs — the lowering only proves "
                "single-output eqns" % (op.prim, len(op.out_ids))))

    ext_in, ext_out = chain_externals(tape, chain)
    lk.ext_in = list(ext_in)
    lk.ext_out = list(ext_out)
    lk.in_avals = [tape.avals[i] for i in ext_in]
    lk.out_avals = [tape.avals[i] for i in ext_out]
    # the auto-declared cost contract: one fused pass reads each
    # external buffer once, writes each output once — EXACTLY the byte
    # model's fused_bytes split (per call; the tape re-applies scale),
    # so declared-vs-tape parity cannot drift
    fused_per_call = lk.fused_bytes // lk.scale
    lk.bytes_written = min(int(chain.external_out_bytes) // lk.scale,
                           fused_per_call)
    lk.bytes_read = fused_per_call - lk.bytes_written
    lk.flops = sum(op.flops for op in ops) // lk.scale
    lk.transcendentals = sum(op.transcendentals for op in ops) // lk.scale

    if lk.findings:
        lk.src = None
        return lk

    var = {}
    for k, iid in enumerate(ext_in):
        var[iid] = "v%d" % iid
    in_params = ["in%d_ref" % k for k in range(len(ext_in))]
    out_params = ["out%d_ref" % k for k in range(len(ext_out))]
    lines = ["def %s(%s):" % (name, ", ".join(in_params + out_params))]
    lines.append('    """mxgen: %s chain of %d eqns (tape %s, rank %d) '
                 "— %d B fused vs %d B unfused.\"\"\""
                 % (chain.kind, lk.n_ops, tag, rank, lk.fused_bytes,
                    lk.unfused_bytes))
    for k, iid in enumerate(ext_in):
        aval = tape.avals[iid]
        shape = tuple(getattr(aval, "shape", ()))
        load = "in%d_ref[...]" % k
        if len(shape) == 0:
            load += ".reshape(())"
        lines.append("    %s = %s  # %s%r" % (var[iid], load,
                                              _dtype_name(aval.dtype),
                                              shape))
    for idx, op in zip(chain.op_indices, ops):
        args = []
        for iid in op.in_ids:
            if iid in var:
                args.append(var[iid])
            else:
                args.append(_literal_src(tape, iid))
        oid = op.out_ids[0]
        var[oid] = "v%d" % oid
        lines.append("    %s = %s" % (var[oid],
                                      _emit_rhs(op.prim, args, op.params)))
    for k, oid in enumerate(ext_out):
        shape = tuple(getattr(tape.avals[oid], "shape", ()))
        store = var[oid]
        if len(shape) == 0:
            store += ".reshape((1,))"
        lines.append("    out%d_ref[...] = %s" % (k, store))
    lk.src = "\n".join(lines) + "\n"
    return lk


# ---------------------------------------------------------------------------
# seeded inputs + the two execution paths
# ---------------------------------------------------------------------------
def seeded_inputs(avals, seed):
    """Deterministic host arrays for a list of avals (the autotune and
    equivalence harness share this)."""
    import numpy as np

    rs = np.random.RandomState(seed)
    out = []
    for aval in avals:
        shape = tuple(getattr(aval, "shape", ()))
        dt = np.dtype(aval.dtype)
        if dt == np.bool_:
            out.append(rs.rand(*shape) > 0.5)
        elif np.issubdtype(dt, np.integer):
            out.append(rs.randint(0, 5, size=shape).astype(dt))
        else:
            out.append(rs.standard_normal(shape).astype(dt))
    return out


class _HostRef:
    """Array stand-in for a Pallas ref so the emitted source can run
    directly on the host (no pallas_call) — the cheap equivalence path
    the budget gate uses."""

    def __init__(self, value=None):
        self.value = value

    def __getitem__(self, _):
        return self.value

    def __setitem__(self, _, val):
        self.value = val


def _exec_namespace():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _dtype(name):
        return jnp.zeros((), dtype=np.dtype(name)).dtype

    return {"jnp": jnp, "lax": jax.lax, "np": np, "_dtype": _dtype}


def compile_kernel_source(lk):
    """exec the emitted source → the kernel function object."""
    ns = _exec_namespace()
    code = compile(lk.src, "<mxgen:%s>" % lk.name, "exec")
    exec(code, ns)
    return ns[lk.name]


def exec_kernel_source(lk, inputs):
    """Run the EMITTED source on host arrays via _HostRef — evaluates
    the very text Pallas would run, without a pallas_call."""
    import jax.numpy as jnp

    fn = compile_kernel_source(lk)
    in_refs = []
    for aval, x in zip(lk.in_avals, inputs):
        x = jnp.asarray(x)
        if x.ndim == 0:
            x = x.reshape((1,))
        in_refs.append(_HostRef(x))
    out_refs = [_HostRef() for _ in lk.ext_out]
    fn(*in_refs, *out_refs)
    outs = []
    for aval, ref in zip(lk.out_avals, out_refs):
        shape = tuple(getattr(aval, "shape", ()))
        v = ref.value
        if len(shape) == 0:
            v = v.reshape(())
        outs.append(v)
    return outs


def reference_outputs(lk, inputs):
    """Interpret the ORIGINAL tape eqns of the chain (path 2)."""
    env = dict(zip(lk.ext_in, inputs))
    tape = lk.tape
    for idx in lk.chain.op_indices:
        op = tape.ops[idx]
        invals = [env[i] if i in env else _literal_val(tape, i)
                  for i in op.in_ids]
        env[op.out_ids[0]] = _prim_eval(op.prim, invals, op.params)
    return [env[i] for i in lk.ext_out]


def equivalence_check_host(lk, seed=EQUIV_SEED, tol=EQUIV_TOL):
    """(ok, max_abs_err): emitted source vs tape interpreter on the same
    seeded inputs.  Float outputs compare at ``tol`` (1e-5, the PR-15
    fused-vs-unfused tolerance); integer/bool outputs compare exactly."""
    import numpy as np

    if lk.src is None:
        return False, float("inf")
    inputs = seeded_inputs(lk.in_avals, seed)
    got = exec_kernel_source(lk, inputs)
    want = reference_outputs(lk, inputs)
    max_err = 0.0
    ok = True
    for g, w in zip(got, want):
        g = np.asarray(g)
        w = np.asarray(w)
        if g.shape != w.shape or g.dtype != w.dtype:
            return False, float("inf")
        if np.issubdtype(g.dtype, np.floating):
            err = float(np.max(np.abs(g - w))) if g.size else 0.0
            max_err = max(max_err, err)
            if not np.allclose(g, w, rtol=tol, atol=tol):
                ok = False
        elif not np.array_equal(g, w):
            ok = False
            max_err = float("inf")
    return ok, max_err


# ---------------------------------------------------------------------------
# autotune: seeded, host-measured, disk-cached, replayed bitwise
# ---------------------------------------------------------------------------
def flat_tileable(lk):
    """True when the kernel can run row-tiled over a (rows, 128) grid:
    a pure elementwise chain whose externals all share one 1-D shape —
    every block sees the same eqns, padding rows are discarded."""
    if lk.src is None or lk.kind != "elementwise":
        return False
    avals = list(lk.in_avals) + list(lk.out_avals)
    shapes = {tuple(getattr(a, "shape", ())) for a in avals}
    if len(shapes) != 1:
        return False
    (shape,) = shapes
    if len(shape) != 1:
        return False
    allowed = set(_ELEMENTWISE_BINOPS) | set(_ELEMENTWISE_UNOPS) \
        | _IDENTITY | {"convert_element_type"}
    return all(p in allowed for p in lk.prims)


def _cache_valid(obj, seed, ladder):
    return (isinstance(obj, dict)
            and obj.get("schema") == AUTOTUNE_CACHE_SCHEMA
            and obj.get("seed") == seed
            and obj.get("ladder") == list(ladder)
            and isinstance(obj.get("kernels"), dict))


def _load_cache(path, seed, ladder):
    try:
        with open(path, "r", encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    return obj if _cache_valid(obj, seed, ladder) else None


def autotune_block_rows(gk, cache_path=None, seed=AUTOTUNE_SEED,
                        ladder=AUTOTUNE_LADDER, reps=AUTOTUNE_REPS):
    """Pick block rows for a flat-tileable generated kernel.

    A valid cache (schema + seed + ladder match, choice on the ladder)
    is REPLAYED — no re-measurement, no rewrite, so two runs sharing a
    cache file agree bitwise.  A corrupt or mismatched cache is rebuilt
    from fresh measurements, never trusted.  Returns the chosen block
    rows (smallest-ladder winner on ties — perf_counter medians over
    ``reps`` runs of the real tiled pallas_call on seeded inputs)."""
    import time

    from ..ops import generated_kernels as gen

    cache_path = cache_path or os.environ.get("MXTPU_MXGEN_CACHE")
    cached = _load_cache(cache_path, seed, ladder) if cache_path else None
    if cached is not None:
        entry = cached["kernels"].get(gk.name)
        if isinstance(entry, dict) and entry.get("block_rows") in ladder:
            return int(entry["block_rows"])

    inputs = seeded_inputs(gk.in_avals, seed)
    times = []
    for br in ladder:
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter_ns()
            outs = gen.generated_call(gk, *inputs, block_rows=br)
            for o in outs:
                o.block_until_ready()
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        times.append(samples[len(samples) // 2])
    best = ladder[times.index(min(times))]

    if cache_path:
        obj = cached or {"schema": AUTOTUNE_CACHE_SCHEMA, "seed": seed,
                         "ladder": list(ladder), "kernels": {}}
        obj["kernels"][gk.name] = {"block_rows": int(best),
                                   "t_ns": [int(t) for t in times]}
        tmp = cache_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(obj, indent=2, sort_keys=True) + "\n")
        os.replace(tmp, cache_path)
    return int(best)


# ---------------------------------------------------------------------------
# the shipped chains: top-3 of the transformer train-step and ZeRO-1
# tapes (the budget models' exact pinned geometries)
# ---------------------------------------------------------------------------
_TAPE_MEMO = {}


def shipped_tape(tag):
    """The flat tape of one target program (memoized per process)."""
    if tag in _TAPE_MEMO:
        return _TAPE_MEMO[tag]
    import jax
    import jax.numpy as jnp

    from . import budget_models as bm

    if tag == "zero1":
        from . import shard_fixtures as sf

        k = bm.DECLARED_AXIS
        step, args = sf.zero1_step_program(k)
        closed = jax.make_jaxpr(step, axis_env=[("data", k)])(*args)
        tape = build_tape(closed, axis_sizes={"data": k})
    elif tag == "tp_transformer":
        from ..transformer import step as tstep

        g = bm.TP_GEOMETRY
        plan, program, _ = bm._tp_plan_and_program()
        n = len(program.param_names)
        step = tstep.build_replica_step(
            program, tstep.sgd_momentum_update(g["momentum"]), [1] * n)
        train_avals = tuple(
            jax.ShapeDtypeStruct(program.local_shape(nm), jnp.float32)
            for nm in program.param_names)
        b_local, t_local = program.local_batch_shape(g["batch"])
        closed = jax.make_jaxpr(step, axis_env=plan.axis_env())(
            train_avals, train_avals,
            jax.ShapeDtypeStruct((b_local, t_local), jnp.int32),
            jax.ShapeDtypeStruct((b_local, t_local), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
            jnp.float32(g["lr"]), jnp.int32(1))
        tape = build_tape(closed, axis_sizes=plan.axis_sizes())
    else:
        raise KeyError(tag)
    _TAPE_MEMO[tag] = tape
    return tape


_LOWERED_MEMO = {}


def shipped_lowered():
    """LoweredKernels for the top-N chains of every shipped tape, in
    (tape, rank) order — deterministic names ``_gen_<tape>_top<rank>``."""
    if "all" in _LOWERED_MEMO:
        return _LOWERED_MEMO["all"]
    out = []
    for tag in SHIPPED_TAPES:
        tape = shipped_tape(tag)
        report = analyze_tape_fusion(tape)
        for rank, chain in enumerate(report.chains[:SHIPPED_TOP_N], 1):
            name = "_gen_%s_top%d" % (tag, rank)
            out.append(lower_chain(tape, chain, name, tag=tag, rank=rank))
    _LOWERED_MEMO["all"] = out
    return out


def shipped_chain_rows():
    """{kernel name: bytes_saved} — the per-chain rows STATIC_BUDGETS.json
    pins (``codegen_chains``) and tools/update_budgets.py regenerates."""
    return {lk.name: int(lk.bytes_saved) for lk in shipped_lowered()}


def codegen_plans():
    """Deterministic lowered plan per shipped chain (``--codegen``)."""
    return [lk.as_plan() for lk in shipped_lowered()]


def render_codegen(plans=None):
    plans = codegen_plans() if plans is None else plans
    lines = ["mxgen: %d shipped chain(s) lowered" % len(plans)]
    for p in plans:
        lines.append(
            "  %-28s %-18s %4d ops  %2d in /%2d out  saves %10d B  %s"
            % (p["name"], "%s#%d:%s" % (p["tape"], p["rank"], p["kind"]),
               p["n_ops"], p["n_inputs"], p["n_outputs"],
               p["bytes_saved"],
               "ok" if p["lowerable"] else ",".join(p["findings"])))
        if p["src"]:
            for ln in p["src"].rstrip("\n").splitlines():
                lines.append("    | " + ln)
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the GEN-rule lint (--self-check)
# ---------------------------------------------------------------------------
def lint_generated_kernels(disable=()):
    """GEN sweep: every shipped chain must lower inside the provable
    set (GEN001), and every REGISTERED generated kernel must carry a
    passing auto-equivalence check (GEN002) — a kernel exec'd into the
    registry without proving itself is an error, not a skip."""
    from ..ops import generated_kernels as gen

    findings = []
    try:
        gen.build_shipped_generated()
    except Exception as e:  # noqa: BLE001 — a broken build IS the finding
        findings.append(Finding(
            "GEN001", "codegen",
            "building the shipped generated kernels failed: %r — the "
            "top chains cannot be proven lowerable" % (e,)))
        return filter_findings(findings, disable)
    for lk in shipped_lowered():
        findings.extend(lk.findings)
    for name in sorted(gen.GENERATED_KERNELS):
        gk = gen.GENERATED_KERNELS[name]
        if not gk.equivalence_ok:
            findings.append(Finding(
                "GEN002", name,
                "generated kernel %r is registered without a passing "
                "auto-equivalence check (emitted source vs tape "
                "interpreter at %g) — an unproven lowering must not "
                "ship" % (name, EQUIV_TOL)))
    return filter_findings(findings, disable)
