"""Finding records and rule metadata for mxlint.

The reference stack validates graphs with dedicated nnvm passes
(``src/executor/infer_graph_attr_pass.cc`` fixpoints, op registration
checks in ``nnvm/src/core/op.cc``); here every check is a pure function
over the registry / Symbol DAG that emits structured ``Finding`` records
instead of aborting, so tooling (CLI, CI, ``Executor.simple_bind(lint=
True)``) can decide how hard to fail.
"""
from __future__ import annotations

import inspect
import re

__all__ = ["Finding", "ERROR", "WARNING", "INFO", "RULES", "severity_rank",
           "suppressed_rules", "filter_findings"]

# severity levels, ordered: findings at ERROR break binding/CI, WARNING
# fails --self-check (the shipped registry must be clean), INFO is advice
ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEV_RANK = {INFO: 0, WARNING: 1, ERROR: 2}


def severity_rank(sev):
    return _SEV_RANK[sev]


# rule_id -> (default severity, one-line description).  docs/analysis.md
# is the user-facing companion of this table; keep the two in sync.
RULES = {
    # registry pass (mxnet_tpu/analysis/registry_lint.py)
    "REG001": (ERROR, "fn cannot accept every declared tensor slot "
                      "(arg_names + aux) positionally"),
    "REG002": (ERROR, "arg_names order contradicts fn's positional "
                      "parameter order"),
    "REG003": (ERROR, "scalar_args entry is not a keyword parameter of fn "
                      "(or collides with a tensor slot)"),
    "REG004": (ERROR, "optional_args entry names no tensor slot, or the "
                      "optional_args callable is not total over defaults"),
    "REG005": (ERROR, "aux input indices are not the contiguous range "
                      "following arg_names"),
    "REG006": (ERROR, "mutates maps an out-of-range input or fn-output "
                      "index"),
    "REG007": (ERROR, "num_outputs callable is not total over fn's "
                      "registered defaults"),
    "REG008": (ERROR, "alias/registration shadows a different op"),
    "REG009": (WARNING, "op has no docstring"),
    "REG010": (WARNING, "op has no entry in the test-coverage map"),
    "REG011": (WARNING, "fn_params introspection failed; positional "
                        "scalar args will map onto arg_names blindly"),
    # graph pass (mxnet_tpu/analysis/graph_lint.py)
    "GRF001": (WARNING, "op output is never consumed and is not a head "
                        "(dead subgraph)"),
    "GRF002": (ERROR, "non-differentiable op sits between a trainable "
                      "argument and a loss head (gradient is cut)"),
    "GRF003": (WARNING, "auxiliary state is read through a non-aux input "
                        "slot (value silently differs train vs. infer)"),
    "GRF004": (WARNING, "float64 appears through dtype promotion from "
                        "narrower inputs (weak-type surprise)"),
    "GRF005": (WARNING, "Reshape bakes a fully-static target shape; any "
                        "batch-size change breaks or recompiles"),
    "GRF006": (WARNING, "constant folded into the compiled graph exceeds "
                        "the size threshold (bloats every executable)"),
    # source pass (mxnet_tpu/analysis/source_lint.py)
    "SRC001": (WARNING, "python scalar capture of array data "
                        "(.item()/.asscalar()/int()/float()) forces a "
                        "trace-time sync and bakes the value in"),
    "SRC002": (WARNING, "python branch on a runtime shape retraces per "
                        "shape (recompile on every new input geometry)"),
    "SRC003": (WARNING, "host-side mean/std normalization in the input "
                        "pipeline: float math on the host and a 4x-wider "
                        "host->device transfer; use the fused device tail "
                        "(ImageRecordIter(device_tail=True) / "
                        "mx.io.make_device_tail)"),
    "SRC004": (WARNING, "per-step blocking host sync inside a training "
                        "loop (float(loss)/.asscalar()/.asnumpy()/"
                        "np.asarray per step): stalls the engine's "
                        "run-ahead dispatch every iteration; accumulate "
                        "on device, use metric.update_lazy, or fetch at "
                        "flush boundaries (engine.bulk / `if step %% k "
                        "== 0` guards)"),
    "SRC005": (WARNING, "unbounded blocking call (.get()/.recv()/.wait()/"
                        ".join() with no timeout) inside a while-loop "
                        "worker/heartbeat loop: a dead peer wedges the "
                        "loop forever; pass a timeout and re-check "
                        "liveness/stop conditions each wake"),
    # meta (mxnet_tpu/analysis/__init__.py self_check)
    "DOC001": (WARNING, "lint rule has no row in the docs/analysis.md "
                        "rule table (keep RULES and the docs in sync)"),
    # telemetry pass (mxnet_tpu/analysis/telemetry_lint.py)
    "TEL001": (ERROR, "chaos probe site drift: a maybe_inject site is "
                      "unregistered in chaos.SITES / registered but "
                      "never probed / missing from the "
                      "docs/observability.md probe table, or "
                      "maybe_inject no longer emits the telemetry "
                      "instant event for fired faults"),
    "TEL002": (ERROR, "attribution phase drift: an add_phase name is "
                      "not declared in attribution.PHASES / a declared "
                      "phase is never measured / the doctor's HINTS map "
                      "or the docs/observability.md phase table "
                      "disagrees with PHASES in either direction"),
    # serving pass (mxnet_tpu/analysis/serving_lint.py)
    "SRV001": (ERROR, "symbol is not batch-polymorphic: shapes are "
                      "data-dependent or baked, so padded-bucket serving "
                      "cannot be recompile-free"),
    "SRV002": (WARNING, "Reshape bakes a static batch dimension; every "
                        "serving bucket compiles (or breaks) separately"),
    "SRV003": (WARNING, "a serving bucket's modeled peak HBM exceeds the "
                        "configured cap (static cost model; the bucket "
                        "would OOM or page at load)"),
    "SRV004": (ERROR, "fleet admission control broken: the summed modeled "
                      "peak HBM of a fleet registration exceeds the cap "
                      "(over-committed packing OOMs under load), or a "
                      "request path binds deadline_ms but calls "
                      "submit()/infer() without propagating it (the "
                      "request can never be shed and rots in the queue)"),
    "SRV005": (ERROR, "wall-clock read in the promotion/capacity decision "
                      "path (time.time/monotonic/perf_counter, "
                      "datetime.now, ...): promotion decisions must come "
                      "from registry metrics and pinned schedules, or "
                      "reruns stop being byte-identical and the audit "
                      "trail stops being replayable"),
    "SRV006": (ERROR, "a decode/prefill path puts sequence geometry "
                      "(length/position/offset) into Python control flow "
                      "or slice bounds: the traced program bakes the "
                      "value as a compile-time constant, so serving "
                      "recompiles per request geometry (or silently "
                      "reuses the wrong program) — keep geometry in "
                      "traced ops (masks, jnp.where, take_along_axis)"),
    # distributed-step pass (mxnet_tpu/analysis/dist_lint.py)
    "DST001": (ERROR, "a trainable parameter's gradient is never "
                      "psum/pmean-reduced over the data axis: replicas "
                      "silently diverge after one step"),
    "DST002": (WARNING, "collective over the data axis applied to an "
                        "already-invariant value: duplicate reduction "
                        "(psum scales by the axis size)"),
    "DST003": (ERROR, "NamedSharding mismatch between the mesh helpers "
                      "and the step inputs (param spec uses the data "
                      "axis, names a missing axis, outranks the param, "
                      "or the batch does not divide the axis)"),
    "DST004": (WARNING, "collective reduction dtype wrong for the wire: "
                        "a sub-f32 float (bf16/f16) reduced over the "
                        "data axis is an ERROR (ring reductions "
                        "accumulate rounding per hop — cast the grads "
                        "to f32 BEFORE the collective, "
                        "docs/precision.md), and an f32+ operand "
                        "widened immediately before the reduction is a "
                        "WARNING (wider wire bytes than the math needs)"),
    "DST005": (WARNING, "step program closes over a baked Python "
                        "constant: iteration-dependent values captured "
                        "at trace time diverge across hosts"),
    # mixed-axis shard rules (mxnet_tpu/analysis/shard_prop.py)
    "DST006": (ERROR, "gradient reduced over the wrong mesh axes: a "
                      "non-data axis it does not vary over, or an axis "
                      "the destination parameter is sharded over "
                      "(summing unrelated shard pieces)"),
    "DST007": (ERROR, "reduce-scatter not paired with the covering "
                      "all-gather before next-step use: the new "
                      "parameter is still a per-rank shard"),
    "DST008": (ERROR, "duplicate/overlapping sub-axis reduction: a "
                      "collective reduces over axes already covered on "
                      "the chain (or with nothing to reduce) — grads "
                      "come out scaled by the axis size"),
    "DST009": (ERROR, "ring-collective schedule broken: a scanned "
                      "ppermute's perm is not a single full ring or "
                      "its hop count differs from the axis size, so "
                      "the modeled bytes do not match the ring formula"),
    "DST010": (ERROR, "activation resharding forced inside the step "
                      "body: operand shardings disagree, so GSPMD "
                      "inserts a hidden all_to_all/all_gather every "
                      "step that no budget accounts for"),
    # pipeline-parallel rules (mxnet_tpu/analysis/shard_prop.py,
    # lint_pipeline_step — docs/pipeline.md)
    "DST011": (ERROR, "pipeline schedule shape broken: the step must "
                      "ppermute activations forward and cotangents "
                      "backward over 'pipe' as full single-cycle rings "
                      "scanned exactly M+K-1 ticks, and modeled peak "
                      "HBM must hold the in-flight microbatch "
                      "activation stash (M x microbatch activations) — "
                      "otherwise the modeled bubble/memory story "
                      "misstates the schedule"),
    "DST012": (ERROR, "gradient of a stage-local (pipe-sharded) "
                      "parameter flows through a reduction over the "
                      "'pipe' axis: stages hold DIFFERENT layers, so "
                      "the update mixes gradients of unrelated "
                      "parameters — reduce pipeline gradients over the "
                      "batch axes only"),
    # cost pass / budget gate (mxnet_tpu/analysis/cost.py, __main__)
    "COST001": (ERROR, "modeled cost metric exceeds its STATIC_BUDGETS "
                       "entry beyond tolerance (or a budgeted model no "
                       "longer builds)"),
    "COST002": (WARNING, "STATIC_BUDGETS entry is stale: the modeled "
                         "metric improved beyond tolerance or a model "
                         "has no budget row — regenerate via "
                         "tools/update_budgets.py"),
    "COST003": (ERROR, "cost pass is nondeterministic: two analyses of "
                       "the same program produced different reports"),
    "COST004": (WARNING, "collective contributes zero modeled wire "
                         "bytes: unknown collective primitive or an "
                         "axis whose size was never declared — the "
                         "collective-byte budget silently understates "
                         "traffic"),
    "COST005": (ERROR, "shipped pallas_call kernel declares no cost "
                       "model: the tape prices it off a once-per-trace "
                       "body walk (wrong in both directions) behind a "
                       "zero-cost connector — register a "
                       "declare_kernel_cost model"),
    "COST006": (ERROR, "generated kernel lost its auto-declared "
                       "KERNEL_COSTS entry: the registry names a mxgen "
                       "kernel the cost pass cannot price — the AST "
                       "sweep (COST005) cannot see exec'd sources, so "
                       "the gap would otherwise be a silent skip"),
    # race pass (mxnet_tpu/analysis/race_lint.py, "mxrace")
    "RACE001": (ERROR, "lock-guard violation: an attribute mutated under "
                       "a lock in one method is read/iterated/written "
                       "bare elsewhere (the PR-6 _key_owner bug class) — "
                       "concurrent mutation can corrupt the bare access"),
    "RACE002": (ERROR, "lock-order hazard: an acquired-while-holding "
                       "cycle (potential deadlock), or the observed "
                       "edge set drifted from the pinned "
                       "docs/concurrency.md lock-hierarchy table in "
                       "either direction"),
    "RACE003": (ERROR, "blocking call under a held lock: socket/RPC "
                       "I/O, unbounded queue get/join, sleep, "
                       "subprocess, or a chaos.maybe_inject site (which "
                       "can delay or raise) inside a lock region stalls "
                       "every contending thread"),
    "RACE004": (ERROR, "Thread started with neither daemon=True nor a "
                       "registered join/shutdown path — it outlives "
                       "shutdown and hangs interpreter exit"),
    "RACE005": (ERROR, "user/foreign callback invoked while holding the "
                       "owner's lock (the PR-6 watchdog class): the "
                       "callback can call back in (deadlock) or block "
                       "the owner unboundedly"),
    # fusion pass (mxnet_tpu/analysis/fusion.py)
    "FUS001": (ERROR, "fused-kernel byte contract broken: the fused "
                      "spelling's modeled HBM bytes do not realize the "
                      "fusion pass's bytes-saved-if-fused for the chain "
                      "it replaces, or the kernel's declared bytes "
                      "differ from one pass over its operands/results"),
    # codegen pass (mxnet_tpu/analysis/codegen.py, "mxgen")
    "GEN001": (ERROR, "fusion chain contains an op outside the "
                      "provable-lowering set: mxgen cannot emit a "
                      "kernel whose semantics it can prove against the "
                      "tape interpreter — the chain stays a "
                      "hand-written-kernel candidate"),
    "GEN002": (ERROR, "generated kernel registered without a passing "
                      "auto-equivalence check: emitted source and tape "
                      "interpreter were never compared at the 1e-5 "
                      "fused-vs-unfused tolerance — an unproven "
                      "lowering must not ship"),
}


class Finding:
    """One lint finding: ``(rule_id, severity, subject, message)``.

    ``subject`` names what the finding is about — an op name for the
    registry pass, a node name for the graph pass, ``file:line`` for the
    source pass.
    """
    __slots__ = ("rule_id", "severity", "subject", "message")

    def __init__(self, rule_id, subject, message, severity=None):
        if rule_id not in RULES:
            raise ValueError("unknown rule_id %r" % (rule_id,))
        self.rule_id = rule_id
        self.severity = severity or RULES[rule_id][0]
        self.subject = subject
        self.message = message

    def as_dict(self):
        return {"rule": self.rule_id, "severity": self.severity,
                "subject": self.subject, "message": self.message}

    def __repr__(self):
        return "Finding(%s, %s, %s)" % (self.rule_id, self.subject,
                                        self.severity)

    def __str__(self):
        return "%-7s %s  %s: %s" % (self.severity.upper(), self.rule_id,
                                    self.subject, self.message)


# ---------------------------------------------------------------------------
# per-op suppression: a ``# mxlint: disable=REG009,GRF005`` comment anywhere
# in the op fn's source (decorator lines included) mutes those rules for
# that op, mirroring pylint's inline pragmas.
# ---------------------------------------------------------------------------
_DISABLE_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Z0-9,\s]+)")


def suppressed_rules(fn):
    """Rule ids disabled via ``# mxlint: disable=...`` in fn's source."""
    try:
        src = inspect.getsource(fn)
    except (OSError, TypeError):
        return frozenset()
    out = set()
    for m in _DISABLE_RE.finditer(src):
        out.update(r.strip() for r in m.group(1).split(",") if r.strip())
    return frozenset(out)


def filter_findings(findings, disable=()):
    """Drop findings whose rule_id is in ``disable``."""
    disable = set(disable)
    return [f for f in findings if f.rule_id not in disable]
