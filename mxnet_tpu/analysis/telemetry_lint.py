"""TEL rules: the chaos fault model and the telemetry trace must agree.

The chaos harness (``resilience/chaos.py``) and the telemetry layer meet
at probe sites: every ``chaos.maybe_inject("site", ...)`` call is both a
fault-injection point and — when a fault fires — a telemetry instant
event + flight-ring record.  Three ways that contract silently drifts,
all caught here as TEL001 (error, wired into ``--self-check`` per the
DOC001 discipline):

- a probe site *used* somewhere in ``mxnet_tpu/`` that is not registered
  in ``chaos.SITES`` (an undocumented fault point: schedules can target
  it but no one knows it exists, and the docs table lies by omission);
- a site *registered* in ``chaos.SITES`` but never probed in the code
  (the fault model advertises a failure mode that can no longer be
  injected — usually a refactor moved the call);
- a registered site missing from the ``docs/observability.md`` probe
  table, or ``chaos.maybe_inject`` no longer stamping fired faults
  through ``telemetry.fault_event`` (the emission point every site's
  "must emit a telemetry instant event" guarantee routes through).

TEL002 applies the same discipline to the performance doctor's
*attribution phases* (``telemetry/attribution.py``): the ``PHASES``
tuple, the ``HINTS`` map the doctor prints from, the
``docs/observability.md`` phase table and the ``add_phase`` call sites
in the shipped sources must all name the same set — a phase measured
but undocumented, documented but unmeasured, or missing its doctor hint
is the attribution layer lying about its own coverage.

Pure AST over the shipped sources — no imports of the probed modules.
"""
from __future__ import annotations

import ast
import glob
import os
import re

from .findings import Finding, filter_findings

__all__ = ["lint_chaos_sites", "probe_sites_used", "SITE_DOC",
           "lint_attribution_phases", "attribution_phases_used",
           "attribution_phase_decls", "context_hint_decls"]

# the documentation the probe table must live in (TEL001's third leg);
# the TEL002 phase table lives in the same doc
SITE_DOC = os.path.join("docs", "observability.md")


def _pkg_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe_sites_used(root=None):
    """Scan ``mxnet_tpu/**/*.py`` (plus the shipped drivers:
    ``bench.py``, ``tools/*.py``) for ``maybe_inject(<literal>, ...)``
    calls.  Returns ``(sites, dynamic)``: ``sites`` maps each literal
    site name to its ``file:line`` use sites; ``dynamic`` lists calls
    whose site argument is not a string literal (unverifiable — those
    are findings too: a computed site name can never be checked against
    the registered fault model)."""
    root = root or _pkg_root()
    repo = os.path.dirname(root)
    sites, dynamic = {}, []
    targets = sorted(glob.glob(os.path.join(root, "**", "*.py"),
                               recursive=True))
    # probe sites also live in the shipped drivers outside the package
    # (bench.py's backend.init, tools/): same fault model, same sweep
    if os.path.isfile(os.path.join(repo, "bench.py")):
        targets.append(os.path.join(repo, "bench.py"))
    targets += sorted(glob.glob(os.path.join(repo, "tools", "*.py")))
    for path in targets:
        rel = os.path.relpath(path, os.path.dirname(root))
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                getattr(fn, "id", None)
            if name != "maybe_inject" or not node.args:
                continue
            where = "%s:%d" % (rel, node.lineno)
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                sites.setdefault(arg.value, []).append(where)
            else:
                dynamic.append(where)
    return sites, dynamic


def _documented_sites(repo):
    """Site names appearing in the docs probe table (a row whose first
    cell is the backticked site name).  None when the doc is absent
    (installed package — the doc legs are skipped silently, the code
    legs still run)."""
    path = os.path.join(repo, SITE_DOC)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        text = f.read()
    return set(re.findall(r"^\|\s*`([a-z_.]+)`", text, re.M))


def _maybe_inject_emits_fault_event(root):
    """chaos.maybe_inject must route fired faults through
    ``telemetry.fault_event`` — the single emission point that makes
    "every probe site emits a telemetry instant event" true by
    construction.  Verified structurally (AST), so deleting the call
    fails ``--self-check`` instead of silently blinding the trace."""
    path = os.path.join(root, "resilience", "chaos.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return False
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "maybe_inject":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    name = fn.attr if isinstance(fn, ast.Attribute) else \
                        getattr(fn, "id", None)
                    if name == "fault_event":
                        return True
    return False


def lint_chaos_sites(disable=(), root=None):
    """The TEL001 sweep (see module docstring).  Returns Finding
    records; empty means fault model, code and docs agree."""
    from ..resilience.chaos import SITES
    root = root or _pkg_root()
    repo = os.path.dirname(root)
    used, dynamic = probe_sites_used(root)
    findings = []
    for site in sorted(set(used) - set(SITES)):
        findings.append(Finding(
            "TEL001", site,
            "chaos probe site %r is used at %s but not registered in "
            "chaos.SITES — an unregistered fault point is invisible to "
            "the fault model and the docs"
            % (site, ", ".join(used[site]))))
    for site in sorted(set(SITES) - set(used)):
        findings.append(Finding(
            "TEL001", site,
            "chaos.SITES registers %r but no maybe_inject call probes "
            "it anywhere in mxnet_tpu/ — the fault model advertises an "
            "injectable failure that no longer exists" % (site,)))
    for where in dynamic:
        findings.append(Finding(
            "TEL001", where,
            "maybe_inject called with a non-literal site name — the "
            "site cannot be checked against the registered fault model"))
    documented = _documented_sites(repo)
    if documented is not None:
        for site in sorted(set(SITES) - documented):
            findings.append(Finding(
                "TEL001", site,
                "chaos probe site %r has no row in the %s probe table "
                "(keep the fault model and the docs in sync)"
                % (site, SITE_DOC)))
    if not _maybe_inject_emits_fault_event(root):
        findings.append(Finding(
            "TEL001", "chaos.maybe_inject",
            "chaos.maybe_inject no longer stamps fired faults through "
            "telemetry.fault_event — injected faults would leave no "
            "instant event or flight-ring record behind"))
    return filter_findings(findings, disable)


# ---------------------------------------------------------------------------
# TEL002: attribution phase names — code, hint map and docs in lockstep
# ---------------------------------------------------------------------------
def attribution_phase_decls(root=None, attribution_path=None):
    """Parse ``telemetry/attribution.py`` (AST, no import) for the
    declared ``PHASES`` tuple and the ``HINTS`` map's literal keys.
    Returns ``(phases, hint_keys)`` as ordered lists; non-literal
    entries come back as None placeholders so the lint can flag them."""
    root = root or _pkg_root()
    path = attribution_path or os.path.join(root, "telemetry",
                                            "attribution.py")
    phases, hint_keys = [], []
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return phases, hint_keys
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        name = getattr(target, "id", None)
        if name == "PHASES" and isinstance(node.value, (ast.Tuple,
                                                        ast.List)):
            for elt in node.value.elts:
                phases.append(elt.value if isinstance(elt, ast.Constant)
                              and isinstance(elt.value, str) else None)
        elif name == "HINTS" and isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                hint_keys.append(key.value if isinstance(key, ast.Constant)
                                 and isinstance(key.value, str) else None)
    return phases, hint_keys


def context_hint_decls(root=None, attribution_path=None):
    """Parse ``telemetry/attribution.py`` (AST, no import) for the
    ``CONTEXT_HINTS`` map's literal ``(phase, tag)`` keys.  Non-literal
    keys come back as None placeholders so the lint can flag them."""
    root = root or _pkg_root()
    path = attribution_path or os.path.join(root, "telemetry",
                                            "attribution.py")
    pairs = []
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return pairs
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        if getattr(node.targets[0], "id", None) != "CONTEXT_HINTS":
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for key in node.value.keys:
            if isinstance(key, ast.Tuple) and len(key.elts) == 2 and \
                    all(isinstance(e, ast.Constant)
                        and isinstance(e.value, str) for e in key.elts):
                pairs.append((key.elts[0].value, key.elts[1].value))
            else:
                pairs.append(None)
    return pairs


def _documented_context_hints(repo, doc_path=None):
    """(phase, tag) rows of the docs context-hint table: the table whose
    header row starts ``| phase | context``.  None when the doc is
    absent (installed package — doc legs skipped)."""
    path = doc_path or os.path.join(repo, SITE_DOC)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        lines = f.read().splitlines()
    pairs = set()
    in_table = False
    for line in lines:
        if re.match(r"^\|\s*phase\s*\|\s*context", line):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                in_table = False
                continue
            m = re.match(r"^\|\s*`([a-z0-9_]+)`\s*\|\s*`([a-z0-9_]+)`",
                         line)
            if m:
                pairs.add((m.group(1), m.group(2)))
    return pairs


def attribution_phases_used(root=None):
    """Scan the shipped sources (``mxnet_tpu/**``, ``bench.py``,
    ``tools/*.py``) for ``add_phase(<literal>, ...)`` calls — the
    attribution instrumentation points.  Returns ``(names, dynamic)``
    exactly like :func:`probe_sites_used`."""
    root = root or _pkg_root()
    repo = os.path.dirname(root)
    names, dynamic = {}, []
    targets = sorted(glob.glob(os.path.join(root, "**", "*.py"),
                               recursive=True))
    if os.path.isfile(os.path.join(repo, "bench.py")):
        targets.append(os.path.join(repo, "bench.py"))
    targets += sorted(glob.glob(os.path.join(repo, "tools", "*.py")))
    for path in targets:
        rel = os.path.relpath(path, os.path.dirname(root))
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                getattr(fn, "id", None)
            if name != "add_phase" or not node.args:
                continue
            where = "%s:%d" % (rel, node.lineno)
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names.setdefault(arg.value, []).append(where)
            else:
                dynamic.append(where)
    return names, dynamic


def _documented_phases(repo, doc_path=None):
    """Phase names in the docs phase table: the table whose header row's
    first cell is ``phase``, rows with a backticked first cell.  None
    when the doc is absent (installed package — doc legs skipped)."""
    path = doc_path or os.path.join(repo, SITE_DOC)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        lines = f.read().splitlines()
    phases = set()
    in_table = False
    for line in lines:
        if re.match(r"^\|\s*phase\s*\|", line):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                in_table = False
                continue
            m = re.match(r"^\|\s*`([a-z0-9_]+)`", line)
            if m:
                phases.add(m.group(1))
    return phases


def lint_attribution_phases(disable=(), root=None, attribution_path=None,
                            doc_path=None):
    """The TEL002 sweep: ``PHASES`` (attribution.py), the ``HINTS``
    doctor map, the docs phase table and the shipped ``add_phase`` call
    sites must agree both ways.  Returns Finding records; empty means
    the attribution layer, the doctor and the docs tell one story."""
    root = root or _pkg_root()
    repo = os.path.dirname(root)
    phases_raw, hints_raw = attribution_phase_decls(
        root, attribution_path=attribution_path)
    findings = []
    if not phases_raw:
        findings.append(Finding(
            "TEL002", "PHASES",
            "telemetry/attribution.py no longer declares a literal "
            "PHASES tuple — the attribution phase set cannot be "
            "verified against the docs or the doctor's hint map"))
        return filter_findings(findings, disable)
    if None in phases_raw or None in hints_raw:
        findings.append(Finding(
            "TEL002", "PHASES",
            "PHASES/HINTS contain non-literal entries — computed phase "
            "names can never be checked against the docs table"))
    phases = {p for p in phases_raw if p}
    hints = {h for h in hints_raw if h}
    used, dynamic = attribution_phases_used(root)
    for name in sorted(set(used) - phases):
        findings.append(Finding(
            "TEL002", name,
            "add_phase(%r) at %s but the phase is not declared in "
            "attribution.PHASES — measured time would be rejected at "
            "runtime and is invisible to the doctor/docs"
            % (name, ", ".join(used[name]))))
    for name in sorted(phases - set(used)):
        findings.append(Finding(
            "TEL002", name,
            "attribution phase %r is declared in PHASES but no "
            "add_phase call measures it anywhere in the shipped "
            "sources — the doctor advertises a decomposition slot that "
            "is always zero" % (name,)))
    for where in dynamic:
        findings.append(Finding(
            "TEL002", where,
            "add_phase called with a non-literal phase name — the phase "
            "cannot be checked against PHASES/docs"))
    for name in sorted(phases - hints):
        findings.append(Finding(
            "TEL002", name,
            "phase %r has no entry in the doctor's HINTS map — a rank "
            "bottlenecked there would get no actionable knob" % (name,)))
    for name in sorted(hints - phases):
        findings.append(Finding(
            "TEL002", name,
            "HINTS names phase %r which is not in PHASES — a stale "
            "doctor hint for a phase that no longer exists" % (name,)))
    documented = _documented_phases(repo, doc_path=doc_path)
    if documented is not None:
        for name in sorted(phases - documented):
            findings.append(Finding(
                "TEL002", name,
                "attribution phase %r has no row in the %s phase table "
                "(keep the decomposition and the docs in sync)"
                % (name, SITE_DOC)))
        for name in sorted(documented - phases):
            findings.append(Finding(
                "TEL002", name,
                "the %s phase table documents %r but attribution.PHASES "
                "does not declare it — the docs promise a phase the "
                "doctor cannot produce" % (SITE_DOC, name)))
    # CONTEXT_HINTS legs: every (phase, tag) specialization must refine
    # a declared phase and have its row in the docs context-hint table
    # (both ways — a stale doc row promises a hint the doctor cannot
    # print)
    ctx_raw = context_hint_decls(root, attribution_path=attribution_path)
    if None in ctx_raw:
        findings.append(Finding(
            "TEL002", "CONTEXT_HINTS",
            "CONTEXT_HINTS contains non-literal (phase, tag) keys — "
            "computed context hints can never be checked against "
            "PHASES or the docs"))
    ctx = {p for p in ctx_raw if p is not None}
    for phase, tag in sorted(ctx):
        if phase not in phases:
            findings.append(Finding(
                "TEL002", "%s:%s" % (phase, tag),
                "CONTEXT_HINTS specializes phase %r (tag %r) which is "
                "not in PHASES — a stale hint for a phase that no "
                "longer exists" % (phase, tag)))
    doc_ctx = _documented_context_hints(repo, doc_path=doc_path)
    if doc_ctx is not None:
        for phase, tag in sorted(ctx - doc_ctx):
            findings.append(Finding(
                "TEL002", "%s:%s" % (phase, tag),
                "context hint (%r, %r) has no row in the %s "
                "context-hint table (keep the doctor's specialized "
                "hints and the docs in sync)" % (phase, tag, SITE_DOC)))
        for phase, tag in sorted(doc_ctx - ctx):
            findings.append(Finding(
                "TEL002", "%s:%s" % (phase, tag),
                "the %s context-hint table documents (%r, %r) but "
                "attribution.CONTEXT_HINTS does not declare it — the "
                "docs promise a hint the doctor cannot print"
                % (SITE_DOC, phase, tag)))
    return filter_findings(findings, disable)
