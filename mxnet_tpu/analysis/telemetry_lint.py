"""TEL rules: the chaos fault model and the telemetry trace must agree.

The chaos harness (``resilience/chaos.py``) and the telemetry layer meet
at probe sites: every ``chaos.maybe_inject("site", ...)`` call is both a
fault-injection point and — when a fault fires — a telemetry instant
event + flight-ring record.  Three ways that contract silently drifts,
all caught here as TEL001 (error, wired into ``--self-check`` per the
DOC001 discipline):

- a probe site *used* somewhere in ``mxnet_tpu/`` that is not registered
  in ``chaos.SITES`` (an undocumented fault point: schedules can target
  it but no one knows it exists, and the docs table lies by omission);
- a site *registered* in ``chaos.SITES`` but never probed in the code
  (the fault model advertises a failure mode that can no longer be
  injected — usually a refactor moved the call);
- a registered site missing from the ``docs/observability.md`` probe
  table, or ``chaos.maybe_inject`` no longer stamping fired faults
  through ``telemetry.fault_event`` (the emission point every site's
  "must emit a telemetry instant event" guarantee routes through).

Pure AST over the shipped sources — no imports of the probed modules.
"""
from __future__ import annotations

import ast
import glob
import os
import re

from .findings import Finding, filter_findings

__all__ = ["lint_chaos_sites", "probe_sites_used", "SITE_DOC"]

# the documentation the probe table must live in (TEL001's third leg)
SITE_DOC = os.path.join("docs", "observability.md")


def _pkg_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe_sites_used(root=None):
    """Scan ``mxnet_tpu/**/*.py`` (plus the shipped drivers:
    ``bench.py``, ``tools/*.py``) for ``maybe_inject(<literal>, ...)``
    calls.  Returns ``(sites, dynamic)``: ``sites`` maps each literal
    site name to its ``file:line`` use sites; ``dynamic`` lists calls
    whose site argument is not a string literal (unverifiable — those
    are findings too: a computed site name can never be checked against
    the registered fault model)."""
    root = root or _pkg_root()
    repo = os.path.dirname(root)
    sites, dynamic = {}, []
    targets = sorted(glob.glob(os.path.join(root, "**", "*.py"),
                               recursive=True))
    # probe sites also live in the shipped drivers outside the package
    # (bench.py's backend.init, tools/): same fault model, same sweep
    if os.path.isfile(os.path.join(repo, "bench.py")):
        targets.append(os.path.join(repo, "bench.py"))
    targets += sorted(glob.glob(os.path.join(repo, "tools", "*.py")))
    for path in targets:
        rel = os.path.relpath(path, os.path.dirname(root))
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                getattr(fn, "id", None)
            if name != "maybe_inject" or not node.args:
                continue
            where = "%s:%d" % (rel, node.lineno)
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                sites.setdefault(arg.value, []).append(where)
            else:
                dynamic.append(where)
    return sites, dynamic


def _documented_sites(repo):
    """Site names appearing in the docs probe table (a row whose first
    cell is the backticked site name).  None when the doc is absent
    (installed package — the doc legs are skipped silently, the code
    legs still run)."""
    path = os.path.join(repo, SITE_DOC)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        text = f.read()
    return set(re.findall(r"^\|\s*`([a-z_.]+)`", text, re.M))


def _maybe_inject_emits_fault_event(root):
    """chaos.maybe_inject must route fired faults through
    ``telemetry.fault_event`` — the single emission point that makes
    "every probe site emits a telemetry instant event" true by
    construction.  Verified structurally (AST), so deleting the call
    fails ``--self-check`` instead of silently blinding the trace."""
    path = os.path.join(root, "resilience", "chaos.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return False
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "maybe_inject":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    name = fn.attr if isinstance(fn, ast.Attribute) else \
                        getattr(fn, "id", None)
                    if name == "fault_event":
                        return True
    return False


def lint_chaos_sites(disable=(), root=None):
    """The TEL001 sweep (see module docstring).  Returns Finding
    records; empty means fault model, code and docs agree."""
    from ..resilience.chaos import SITES
    root = root or _pkg_root()
    repo = os.path.dirname(root)
    used, dynamic = probe_sites_used(root)
    findings = []
    for site in sorted(set(used) - set(SITES)):
        findings.append(Finding(
            "TEL001", site,
            "chaos probe site %r is used at %s but not registered in "
            "chaos.SITES — an unregistered fault point is invisible to "
            "the fault model and the docs"
            % (site, ", ".join(used[site]))))
    for site in sorted(set(SITES) - set(used)):
        findings.append(Finding(
            "TEL001", site,
            "chaos.SITES registers %r but no maybe_inject call probes "
            "it anywhere in mxnet_tpu/ — the fault model advertises an "
            "injectable failure that no longer exists" % (site,)))
    for where in dynamic:
        findings.append(Finding(
            "TEL001", where,
            "maybe_inject called with a non-literal site name — the "
            "site cannot be checked against the registered fault model"))
    documented = _documented_sites(repo)
    if documented is not None:
        for site in sorted(set(SITES) - documented):
            findings.append(Finding(
                "TEL001", site,
                "chaos probe site %r has no row in the %s probe table "
                "(keep the fault model and the docs in sync)"
                % (site, SITE_DOC)))
    if not _maybe_inject_emits_fault_event(root):
        findings.append(Finding(
            "TEL001", "chaos.maybe_inject",
            "chaos.maybe_inject no longer stamps fired faults through "
            "telemetry.fault_event — injected faults would leave no "
            "instant event or flight-ring record behind"))
    return filter_findings(findings, disable)
