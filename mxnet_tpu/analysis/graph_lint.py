"""Graph lint: whole-DAG checks over a Symbol before it is bound.

Reference: the nnvm shape/type fixpoints (``src/executor/
infer_graph_attr_pass.cc``) only prove inferability; the classes caught
here — gradient-cutting ops on a loss path, aux state read as a plain
tensor, accidental float64 promotion, recompile-forcing static shapes,
megabyte constants folded into the jaxpr — surface in the reference as
runtime asserts or, worse, silent slowness inside ``jax.jit``.
"""
from __future__ import annotations

import numpy as _np

from ..ops import registry as _reg
from .findings import Finding, filter_findings

__all__ = ["lint_graph", "LOSS_OPS", "LARGE_CONST_BYTES"]

# output heads that start a gradient (the reference marks these via
# MakeLoss/grad_scale semantics); ancestors of these carry the backward pass
LOSS_OPS = frozenset({
    "SoftmaxOutput", "LinearRegressionOutput", "MAERegressionOutput",
    "LogisticRegressionOutput", "SVMOutput", "MakeLoss",
    "IdentityAttachKLSparseReg", "softmax_cross_entropy", "CTCLoss",
    "_contrib_CTCLoss",
})

# constants above this folded into the compiled program get copied into
# every executable and resident in HBM per-donation — flag them
LARGE_CONST_BYTES = 1 << 20

# Reshape dim codes (0 = copy, -1 = infer, -2.. = advanced) keep the graph
# batch-polymorphic; a fully positive literal shape does not
_RESHAPE_OPS = frozenset({"Reshape", "reshape"})


def _node_params(op, node):
    from ..symbol.symbol import _attr_params
    return _attr_params(op, node.attrs)


def _n_outputs(node):
    op = _reg.get(node.op)
    try:
        return op.n_outputs(_node_params(op, node))
    except Exception:
        return 1


def _ancestors(roots):
    """All nodes reachable upward (through inputs) from ``roots``."""
    seen = set()
    stack = list(roots)
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        stack.extend(c for c, _ in n.inputs)
    return seen


def _lint_dead_outputs(nodes, heads):
    consumed = {(id(n), oi) for node in nodes for n, oi in node.inputs}
    consumed |= {(id(n), oi) for n, oi in heads}
    out = []
    for n in nodes:
        if n.op is None:
            continue
        for i in range(_n_outputs(n)):
            if (id(n), i) not in consumed:
                out.append(Finding(
                    "GRF001", n.name,
                    "output %d of %s is neither consumed nor a head; the "
                    "subgraph computing it is dead weight" % (i, n.op)))
    return out


def _lint_nondiff_path(nodes, heads):
    loss_nodes = [n for n, _ in heads if n.op in LOSS_OPS]
    if not loss_nodes:
        return []
    above_loss = _ancestors(loss_nodes)
    out = []
    for n in nodes:
        if n.op is None or id(n) not in above_loss or n.op in LOSS_OPS:
            continue
        op = _reg.get(n.op)
        if op.differentiable:
            continue
        # only a problem if a trainable argument sits beneath the cut
        below = _ancestors([c for c, _ in n.inputs])
        has_param_below = any(a.op is None and not a._is_aux
                              for a in nodes if id(a) in below)
        if has_param_below:
            out.append(Finding(
                "GRF002", n.name,
                "%s is differentiable=False yet sits on the path from "
                "trainable arguments to a loss head — their gradient "
                "through this node is zero" % (n.op,)))
    return out


def _lint_aux_reads(nodes):
    out = []
    for n in nodes:
        if n.op is None:
            continue
        op = _reg.get(n.op)
        for pos, (child, _) in enumerate(n.inputs):
            if child.op is None and child._is_aux and pos not in op.aux:
                out.append(Finding(
                    "GRF003", n.name,
                    "aux state %r feeds non-aux input slot %d of %s; its "
                    "value differs between training and inference and this "
                    "read will not see in-place updates" %
                    (child.name, pos, n.op)))
    return out


def _lint_float64(nodes, type_dict):
    """Mirror Symbol.infer_type's promotion walk, flagging the node that
    first widens to float64 from narrower inputs."""
    f64 = _np.dtype(_np.float64)
    env = {}
    out = []
    for n in nodes:
        if n.op is None:
            dt = type_dict.get(n.name)
            if dt is None and "__dtype__" in n.attrs:
                dt = n.attrs["__dtype__"]
            env[id(n)] = _np.dtype(dt) if dt is not None else \
                _np.dtype(_np.float32)
            continue
        if n.op in ("Cast", "cast"):
            env[id(n)] = _np.dtype(
                _reg.canonicalize(n.attrs.get("dtype", "float32")))
            if env[id(n)] == f64:
                ins = [env.get(id(c)) for c, _ in n.inputs]
                if all(d != f64 for d in ins if d is not None):
                    out.append(Finding(
                        "GRF004", n.name,
                        "Cast widens %s to float64; on TPU float64 is "
                        "emulated and an order of magnitude slower" %
                        ([str(d) for d in ins if d is not None],)))
            continue
        ins = [env.get(id(c)) for c, _ in n.inputs]
        ins = [d for d in ins if d is not None]
        dt = _np.dtype(_np.result_type(*ins)) if ins else \
            _np.dtype(_np.float32)
        env[id(n)] = dt
        if dt == f64 and ins and any(d != f64 for d in ins):
            out.append(Finding(
                "GRF004", n.name,
                "%s promotes %s to float64 (weak-type surprise: check "
                "variable dtypes %s)" %
                (n.op, sorted({str(d) for d in ins if d != f64}),
                 sorted({c.name for c, _ in n.inputs if c.op is None}))))
    return out


def _lint_static_reshape(nodes):
    out = []
    for n in nodes:
        if n.op not in _RESHAPE_OPS:
            continue
        shape = _reg.canonicalize(n.attrs.get("shape", ()))
        if not isinstance(shape, (tuple, list)) or len(shape) < 2:
            continue
        if all(isinstance(d, int) and d > 0 for d in shape):
            out.append(Finding(
                "GRF005", n.name,
                "Reshape target %r is fully static; use 0 (copy) or -1 "
                "(infer) dim codes so a batch-size change does not break "
                "the graph or force a recompile" % (tuple(shape),)))
    return out


def _lint_large_consts(symbol, shapes, type_dict):
    """Trace the graph with jax.make_jaxpr and flag closure-captured
    constants above LARGE_CONST_BYTES (they are baked into every compiled
    executable)."""
    import jax

    from ..symbol.symbol import _infer_entry_shapes, make_graph_fn
    known = {k: tuple(v) for k, v in (shapes or {}).items() if v is not None}
    entry_shapes, ok = _infer_entry_shapes(symbol._outputs, known, type_dict)
    if not ok:
        return []   # underspecified graph: nothing to trace
    nodes = symbol._nodes()
    args, aux = {}, {}
    for n in nodes:
        if n.op is not None:
            continue
        s = entry_shapes.get((id(n), 0))
        if s is None:
            return []
        (aux if n._is_aux else args)[n.name] = s
    graph_fn = make_graph_fn(symbol, train=False)
    try:
        closed = jax.make_jaxpr(graph_fn)(args, aux, jax.random.PRNGKey(0))
    except Exception:
        return []   # graph doesn't trace — execution will report it
    out = []
    for const in closed.consts:
        nbytes = getattr(const, "nbytes", 0)
        if nbytes > LARGE_CONST_BYTES:
            out.append(Finding(
                "GRF006", symbol.name or "<graph>",
                "constant of shape %s (%s, %.1f MiB) is folded into the "
                "jaxpr; pass it as an argument instead of closing over it" %
                (tuple(getattr(const, "shape", ())),
                 getattr(const, "dtype", "?"), nbytes / (1 << 20))))
    return out


def lint_graph(symbol, shapes=None, type_dict=None, disable=(),
               check_consts=True):
    """Run every graph rule over ``symbol``.

    ``shapes``: {arg_name: shape} enabling the trace-based GRF006 check;
    ``type_dict``: {arg_name: dtype} for the float64 promotion walk.
    """
    nodes = symbol._nodes()
    heads = symbol._outputs
    tdict = {k: _np.dtype(v) for k, v in (type_dict or {}).items()}
    findings = []
    findings += _lint_dead_outputs(nodes, heads)
    findings += _lint_nondiff_path(nodes, heads)
    findings += _lint_aux_reads(nodes)
    findings += _lint_float64(nodes, tdict)
    findings += _lint_static_reshape(nodes)
    if check_consts:
        findings += _lint_large_consts(symbol, shapes, tdict)
    # node-level suppression: a __mxlint_disable__ attr on the node mutes
    # the listed rules for findings it subjects
    by_name = {n.name: n for n in nodes}
    kept = []
    for f in findings:
        node = by_name.get(f.subject)
        muted = ()
        if node is not None and "__mxlint_disable__" in node.attrs:
            muted = [r.strip() for r in
                     str(node.attrs["__mxlint_disable__"]).split(",")]
        if f.rule_id not in muted:
            kept.append(f)
    return filter_findings(kept, disable)
