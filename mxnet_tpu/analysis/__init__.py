"""mxnet_tpu.analysis — "mxlint", static graph/registry analysis.

The reference stack proves graph attributes with dedicated nnvm passes
(``src/executor/infer_graph_attr_pass.cc``); the JAX reproduction had no
analogue, so a malformed op registration or a recompile-forcing pattern
only failed deep inside ``jax.jit``.  This package closes that gap with
three cooperating passes:

- **registry lint** (:mod:`.registry_lint`): per-op metadata vs. the real
  fn signature — slot counts/order, scalar/optional/aux/mutates indices,
  ``num_outputs`` totality, alias shadowing, docstrings, test coverage;
- **graph lint** (:mod:`.graph_lint`): whole-Symbol checks — dead
  outputs, gradient-cutting ops on loss paths, aux misuse, float64
  promotion, static reshapes, oversized baked-in constants;
- **source lint** (:mod:`.source_lint`): AST heuristics over driver
  scripts for trace-time scalar captures and shape-dependent branching.

Entry points: ``python -m mxnet_tpu.analysis`` (CLI), ``Symbol.lint()``,
``Module.lint()`` and ``Executor.simple_bind(..., lint=True)``.
"""
from __future__ import annotations

from .findings import (Finding, RULES, ERROR, WARNING, INFO,
                       filter_findings, suppressed_rules)
from .registry_lint import lint_registry, unique_ops
from .graph_lint import lint_graph, LOSS_OPS, LARGE_CONST_BYTES
from .source_lint import lint_source, lint_file
from .serving_lint import lint_serving
from .coverage import load_test_map, generate_coverage_md
from .report import render_text, render_json, exit_code, worst_severity

__all__ = [
    "Finding", "RULES", "ERROR", "WARNING", "INFO",
    "lint_registry", "lint_graph", "lint_source", "lint_file",
    "lint_symbol", "lint_serving", "self_check", "load_test_map",
    "generate_coverage_md",
    "render_text", "render_json", "exit_code", "worst_severity",
    "filter_findings", "suppressed_rules", "unique_ops",
    "LOSS_OPS", "LARGE_CONST_BYTES",
]


def lint_symbol(symbol, shapes=None, type_dict=None, disable=(),
                check_consts=True):
    """Graph-lint a Symbol (the ``Symbol.lint()`` implementation)."""
    return lint_graph(symbol, shapes=shapes, type_dict=type_dict,
                      disable=disable, check_consts=check_consts)


def self_check(disable=(), with_coverage=True):
    """Registry lint over the live registry — what CI runs.

    Returns the findings list; clean means the shipped registry is sound
    (every severity counts: ``--self-check`` exits non-zero on warnings).
    """
    coverage_map = load_test_map() if with_coverage else None
    return lint_registry(coverage_map=coverage_map, disable=disable)
