"""mxnet_tpu.analysis — "mxlint", static graph/registry analysis.

The reference stack proves graph attributes with dedicated nnvm passes
(``src/executor/infer_graph_attr_pass.cc``); the JAX reproduction had no
analogue, so a malformed op registration or a recompile-forcing pattern
only failed deep inside ``jax.jit``.  This package closes that gap with
three cooperating passes:

- **registry lint** (:mod:`.registry_lint`): per-op metadata vs. the real
  fn signature — slot counts/order, scalar/optional/aux/mutates indices,
  ``num_outputs`` totality, alias shadowing, docstrings, test coverage;
- **graph lint** (:mod:`.graph_lint`): whole-Symbol checks — dead
  outputs, gradient-cutting ops on loss paths, aux misuse, float64
  promotion, static reshapes, oversized baked-in constants;
- **source lint** (:mod:`.source_lint`): AST heuristics over driver
  scripts for trace-time scalar captures and shape-dependent branching.

Entry points: ``python -m mxnet_tpu.analysis`` (CLI), ``Symbol.lint()``,
``Module.lint()`` and ``Executor.simple_bind(..., lint=True)``.
"""
from __future__ import annotations

from .findings import (Finding, RULES, ERROR, WARNING, INFO,
                       filter_findings, suppressed_rules)
from .registry_lint import lint_registry, unique_ops
from .graph_lint import lint_graph, LOSS_OPS, LARGE_CONST_BYTES
from .source_lint import lint_source, lint_file
from .serving_lint import (lint_serving, lint_fleet_hbm,
                           lint_deadline_propagation)
from .mlops_lint import (lint_wallclock_reads, lint_promotion_sources,
                         lint_supervisor_sources)
from .telemetry_lint import (lint_chaos_sites, probe_sites_used,
                             lint_attribution_phases,
                             attribution_phases_used,
                             attribution_phase_decls)
from .coverage import load_test_map, generate_coverage_md
from .report import (render_text, render_json, exit_code, worst_severity,
                     SCHEMA_VERSION)
from .cost import (CostReport, analyze_jaxpr, analyze_fn, analyze_symbol,
                   XLA_FLOP_RTOL, ring_bytes_per_axis, unpriced_findings,
                   KERNEL_COSTS, declare_kernel_cost)
from .fusion import (FusionReport, FusionChain, analyze_tape_fusion,
                     fusion_from_jaxpr, fusion_from_fn,
                     fusion_for_symbol, lint_kernel_costs,
                     FUSION_HINT_MIN_PCT)
from .codegen import (LoweredKernel, lower_chain, LOWERABLE,
                      lint_generated_kernels, codegen_plans,
                      render_codegen, equivalence_check_host,
                      shipped_lowered, shipped_chain_rows,
                      autotune_block_rows, AUTOTUNE_LADDER,
                      AUTOTUNE_SEED)
from .dist_lint import lint_dist_step, lint_trainer, dist_summary
from .race_lint import (lint_race_source, lint_race_file,
                        lint_threaded_sources, lock_order_findings,
                        parse_hierarchy, race_summary, threaded_targets)
from .shard_prop import (MeshSpec, ShardSpec, ShardReport, propagate,
                         collective_schedule, lint_sharded_step,
                         lint_ring_schedule, lint_global_sharding,
                         shard_summary)

__all__ = [
    "Finding", "RULES", "ERROR", "WARNING", "INFO",
    "lint_registry", "lint_graph", "lint_source", "lint_file",
    "lint_symbol", "lint_serving", "lint_fleet_hbm",
    "lint_deadline_propagation", "lint_serving_sources",
    "lint_decode_sources", "lint_decode_trace_constants",
    "lint_wallclock_reads", "lint_promotion_sources",
    "lint_supervisor_sources",
    "lint_rule_docs", "self_check",
    "lint_shipped_loops", "lint_worker_loops",
    "lint_chaos_sites", "probe_sites_used", "lint_attribution_phases",
    "attribution_phases_used", "attribution_phase_decls",
    "load_test_map",
    "generate_coverage_md",
    "render_text", "render_json", "exit_code", "worst_severity",
    "filter_findings", "suppressed_rules", "unique_ops",
    "LOSS_OPS", "LARGE_CONST_BYTES",
    "CostReport", "analyze_jaxpr", "analyze_fn", "analyze_symbol",
    "XLA_FLOP_RTOL", "SCHEMA_VERSION", "ring_bytes_per_axis",
    "unpriced_findings",
    "lint_dist_step", "lint_trainer", "dist_summary", "cost_self_check",
    "MeshSpec", "ShardSpec", "ShardReport", "propagate",
    "collective_schedule", "lint_sharded_step", "lint_ring_schedule",
    "lint_global_sharding", "shard_summary", "shard_self_check",
    "lint_parallel_sources",
    "FusionReport", "FusionChain", "analyze_tape_fusion",
    "fusion_from_jaxpr", "fusion_from_fn", "fusion_for_symbol",
    "lint_kernel_costs", "FUSION_HINT_MIN_PCT", "KERNEL_COSTS",
    "declare_kernel_cost",
    "LoweredKernel", "lower_chain", "LOWERABLE",
    "lint_generated_kernels", "codegen_plans", "render_codegen",
    "equivalence_check_host", "shipped_lowered", "shipped_chain_rows",
    "autotune_block_rows", "AUTOTUNE_LADDER", "AUTOTUNE_SEED",
    "lint_race_source", "lint_race_file", "lint_threaded_sources",
    "lock_order_findings", "parse_hierarchy", "race_summary",
    "threaded_targets",
]


def lint_symbol(symbol, shapes=None, type_dict=None, disable=(),
                check_consts=True):
    """Graph-lint a Symbol (the ``Symbol.lint()`` implementation)."""
    return lint_graph(symbol, shapes=shapes, type_dict=type_dict,
                      disable=disable, check_consts=check_consts)


def self_check(disable=(), with_coverage=True, with_cost=True,
               with_examples=True, with_workers=True, with_serving=True,
               with_telemetry=True, with_shard=True, with_mlops=True,
               with_race=True, with_codegen=True):
    """Registry lint over the live registry, the rule-table docs sync
    check, the cost-pass determinism check, the SRC004 sweep over the
    shipped training loops, the SRC005 sweep over the shipped worker
    loops, the SRV004 deadline-propagation sweep over the shipped
    serving request paths, the SRV005 wall-clock sweep over the
    promotion/capacity decision path (``mlops/`` + the decision CLIs),
    the telemetry sweeps — TEL001 chaos-probe sites and TEL002
    attribution phases + context hints — the mxshard sweeps: the golden
    sharded-step fixtures must lint clean and deterministically
    (``shard_self_check``) and the shipped ring/Ulysses attention paths
    must pass the mixed-axis DST rules (``lint_parallel_sources``) —
    and the declared-cost sweep over the shipped Pallas kernels
    (``lint_kernel_costs``, COST005/COST006) and the mxgen sweep over
    the generated kernels (``lint_generated_kernels``, GEN001/GEN002:
    every shipped chain lowers provably and every registered generated
    kernel passed its auto-equivalence check) — plus the mxrace
    concurrency
    sweep over every threaded host module (``lint_threaded_sources``:
    RACE001-RACE005, the lock-order/hierarchy sync against
    ``docs/concurrency.md``, and race-report determinism) — what CI
    runs.

    Returns the findings list; clean means the shipped registry is sound
    (every severity counts: ``--self-check`` exits non-zero on warnings).
    """
    coverage_map = load_test_map() if with_coverage else None
    findings = lint_registry(coverage_map=coverage_map, disable=disable)
    findings += lint_rule_docs(disable=disable)
    if with_cost:
        findings += cost_self_check(disable=disable)
    if with_examples:
        findings += lint_shipped_loops(disable=disable)
    if with_workers:
        findings += lint_worker_loops(disable=disable)
    if with_serving:
        findings += lint_serving_sources(disable=disable)
        findings += lint_decode_sources(disable=disable)
    if with_mlops:
        findings += lint_promotion_sources(disable=disable)
        findings += lint_supervisor_sources(disable=disable)
    if with_telemetry:
        findings += lint_chaos_sites(disable=disable)
        findings += lint_attribution_phases(disable=disable)
    if with_shard:
        findings += shard_self_check(disable=disable)
        findings += lint_parallel_sources(disable=disable)
    if with_race:
        findings += lint_threaded_sources(disable=disable)
    if with_codegen:
        # the mxgen sweep (GEN001/GEN002): every shipped chain lowers
        # inside the provable set and every registered generated kernel
        # carries a passing auto-equivalence check
        findings += lint_generated_kernels(disable=disable)
    if with_cost:
        # the declared-cost sweep (COST005 + the COST006 registry diff
        # for exec'd mxgen kernels): every shipped pallas_call must
        # price itself — an un-annotated kernel fails CI here.  Runs
        # AFTER the codegen sweep so the generated registry is built
        findings += lint_kernel_costs(disable=disable)
    return findings


def lint_serving_sources(disable=()):
    """SRV004 (deadline-propagation half) over every shipped serving
    request path: the serving package itself, the serve CLI and the
    serving examples.  A shipped path that binds ``deadline_ms`` but
    drops it before the Batcher breaks admission control for anyone
    copying it.  (The packing half of SRV004 runs at every
    ``ModelFleet.register`` — it needs live modeled costs, not source.)
    Skipped silently outside a repo checkout."""
    import glob
    import os

    pkg = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(pkg)          # mxnet_tpu/
    repo = os.path.dirname(root)
    targets = sorted(glob.glob(os.path.join(root, "serving", "*.py")))
    if os.path.isfile(os.path.join(repo, "tools", "serve.py")):
        targets.append(os.path.join(repo, "tools", "serve.py"))
    if os.path.isdir(os.path.join(repo, "examples", "serving")):
        targets += sorted(glob.glob(os.path.join(
            repo, "examples", "serving", "*.py")))
    findings = []
    for path in targets:
        try:
            findings += lint_deadline_propagation(os.path.normpath(path))
        except OSError:
            continue
    return filter_findings(findings, disable)


def lint_decode_sources(disable=()):
    """SRV006 over the shipped decode tier: the serving package (the
    DecodeRunner/DecodeBatcher host paths) plus the traced phase
    spellings in ``mxnet_tpu/transformer/decode.py``.  A decode path
    that bakes sequence length or batch position into a trace constant
    recompiles per request geometry — the exact contract the
    prefill/decode split exists to keep.  Skipped silently outside a
    repo checkout."""
    import glob
    import os

    from .serving_lint import lint_decode_trace_constants

    pkg = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(pkg)          # mxnet_tpu/
    targets = sorted(glob.glob(os.path.join(root, "serving", "*.py")))
    tdec = os.path.join(root, "transformer", "decode.py")
    if os.path.isfile(tdec):
        targets.append(tdec)
    findings = []
    for path in targets:
        try:
            findings += lint_decode_trace_constants(os.path.normpath(path))
        except OSError:
            continue
    return filter_findings(findings, disable)


def lint_shipped_loops(disable=()):
    """SRC004 over every ``examples/`` script and the in-repo fit loops
    (``module/base_module.py``, ``parallel/trainer.py``,
    ``monitor.py``): the training loops this repo ships must not block
    the host once per dispatched step — the engine's run-ahead window would collapse to 1 for anyone
    copying them.  Only SRC004 is kept (the other source rules are
    advisory for user scripts; examples demonstrate plenty of idioms
    they would flag).  Skipped silently outside a repo checkout."""
    import glob
    import os

    pkg = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(pkg))
    examples = os.path.join(repo, "examples")
    if not os.path.isdir(examples):
        return []
    targets = sorted(glob.glob(os.path.join(examples, "**", "*.py"),
                               recursive=True))
    targets += [os.path.join(pkg, os.pardir, "module", "base_module.py"),
                os.path.join(pkg, os.pardir, "parallel", "trainer.py"),
                # the legacy Monitor used to block per batch; its lazy
                # toc-boundary drain keeps it in the sweep, not a hole
                os.path.join(pkg, os.pardir, "monitor.py")]
    findings = []
    for path in targets:
        try:
            found = lint_file(os.path.normpath(path))
        except (OSError, ValueError):
            continue
        findings += [f for f in found if f.rule_id == "SRC004"]
    return filter_findings(findings, disable)


def lint_worker_loops(disable=()):
    """SRC005 over every shipped concurrency surface: the pipeline's
    worker processes, the PS server/client loops, the serving batcher,
    the resilience heartbeat/watchdog threads, the run-ahead engine, the
    data loader, the launcher and all examples.  A worker loop this repo
    ships must never block unboundedly on a peer that can die — the exact
    wedge class behind the BENCH_r03..r05 backend-init hangs.  Skipped
    silently outside a repo checkout."""
    import glob
    import os

    pkg = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(pkg)          # mxnet_tpu/
    repo = os.path.dirname(root)
    targets = sorted(
        glob.glob(os.path.join(root, "io", "*.py"))
        + glob.glob(os.path.join(root, "serving", "*.py"))
        + glob.glob(os.path.join(root, "resilience", "*.py"))
        + glob.glob(os.path.join(root, "gluon", "data", "*.py")))
    targets += [os.path.join(root, "engine.py"),
                os.path.join(root, "kvstore.py"),
                os.path.join(root, "kvstore_ps.py"),
                os.path.join(root, "kvstore_server.py"),
                os.path.join(root, "parallel", "trainer.py")]
    if os.path.isdir(os.path.join(repo, "tools")):
        targets += sorted(glob.glob(os.path.join(repo, "tools", "*.py")))
    if os.path.isdir(os.path.join(repo, "examples")):
        targets += sorted(glob.glob(os.path.join(repo, "examples", "**",
                                                 "*.py"), recursive=True))
    findings = []
    for path in targets:
        try:
            found = lint_file(os.path.normpath(path))
        except (OSError, ValueError):
            continue
        findings += [f for f in found if f.rule_id == "SRC005"]
    return filter_findings(findings, disable)


def cost_self_check(disable=()):
    """COST003: the cost pass must be deterministic — two analyses of
    the same fixture program (an MLP forward + a collective step) must
    produce byte-identical reports, or STATIC_BUDGETS.json gating would
    flap in CI."""
    import jax.numpy as jnp
    from jax import lax

    def fixture(w1, w2, x):
        h = jnp.maximum(x @ w1, 0.0)
        g = lax.pmean(h @ w2, "data")
        return jnp.exp(g).sum()

    args = (jnp.zeros((16, 32)), jnp.zeros((32, 8)), jnp.zeros((4, 16)))
    reports = [analyze_fn(fixture, *args, axis_env=[("data", 8)],
                          donate_argnums=(0,), host_argnums=(2,))
               .as_dict() for _ in range(2)]
    findings = []
    if reports[0] != reports[1]:
        diff = sorted(k for k in reports[0]
                      if reports[0][k] != reports[1].get(k))
        findings.append(Finding(
            "COST003", "cost_self_check",
            "two runs of the cost pass over the same program disagree "
            "on %s — the budget gate would flap" % (diff,)))
    return filter_findings(findings, disable)


def shard_self_check(disable=()):
    """mxshard sweep for ``--self-check``: the three canonical sharded
    patterns (docs/analysis.md "Sharding propagation") must lint clean
    under the mixed-axis DST rules, and the propagation must be
    deterministic — the golden fixtures are miniatures (the full
    budgeted geometries run in the budget gate / tests)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import shard_prop as sp
    from .shard_fixtures import tp_matmul_program

    findings = []
    k = 4
    mesh = sp.MeshSpec({"data": k})

    # mini ZeRO-1: reduce-scatter / shard-update / all-gather round trip
    def mini_zero1(w, m_sh, x):
        loss, g = jax.value_and_grad(
            lambda w: ((x @ w) ** 2).mean())(w)
        g_sh = lax.psum_scatter(g.ravel(), "data", scatter_dimension=0,
                                tiled=True) / k
        idx = lax.axis_index("data")
        n = w.size // k
        w_sh = lax.dynamic_slice(w.ravel(), (idx * n,), (n,))
        new_m = 0.9 * m_sh + g_sh
        new_flat = lax.all_gather(w_sh - 0.1 * new_m, "data", tiled=True)
        return lax.pmean(loss, "data"), new_flat.reshape(w.shape), new_m

    w = jax.ShapeDtypeStruct((16, 8), jnp.float32)
    m = jax.ShapeDtypeStruct((16 * 8 // k,), jnp.float32)
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    closed = jax.make_jaxpr(mini_zero1, axis_env=[("data", k)])(w, m, x)
    findings += sp.lint_sharded_step(
        closed, mesh, data_axes=("data",), varying_invars=[2],
        shard_dims={1: {0: ("data",)}}, param_outvars=[1],
        param_names=["w"], subject="shard_self_check.zero1")

    # mini tensor-parallel matmul: exactly one inferred psum over model
    fn, args, specs = tp_matmul_program(batch=8, d_in=8, d_mid=16,
                                        d_out=4)
    tmesh = sp.MeshSpec({"data": 4, "model": 2})
    tclosed = jax.make_jaxpr(fn)(*args)
    reports = [sp.propagate(tclosed, tmesh, specs).as_dict()
               for _ in range(2)]
    if reports[0] != reports[1]:
        findings.append(Finding(
            "COST003", "shard_self_check",
            "two runs of the shard propagation over the same program "
            "disagree — the shard section of the budget gate would "
            "flap"))
    inferred = [ev for ev in reports[0]["schedule"]
                if ev["inferred"] and "model" in ev["axes"]]
    if not inferred:
        findings.append(Finding(
            "COST003", "shard_self_check",
            "the tensor-parallel matmul fixture no longer infers its "
            "partial-sum psum over the model axis — the propagation "
            "lost the GSPMD contraction rule"))
    for ev in reports[0]["reshards"]:
        findings.append(Finding(
            "DST010", "shard_self_check",
            "the clean tensor-parallel fixture reports a forced "
            "reshard (%r) — propagation regression" % (ev,)))

    # mini ring: a scanned full-ring ppermute must satisfy DST009
    def mini_ring(x):
        perm = [(i, (i + 1) % k) for i in range(k)]
        def hop(c, _):
            return lax.ppermute(c, "seq", perm), ()
        out, _ = lax.scan(hop, x, jnp.arange(k))
        return out

    rclosed = jax.make_jaxpr(mini_ring, axis_env=[("seq", k)])(
        jax.ShapeDtypeStruct((8, 8), jnp.float32))
    findings += sp.lint_ring_schedule(rclosed, "seq", k,
                                      subject="shard_self_check.ring")
    return filter_findings(findings, disable)


def lint_parallel_sources(disable=()):
    """The mixed-axis shard passes over the shipped sequence-parallel
    attention paths (``parallel/ring_attention.py``): ring attention
    forward+backward must prove its ppermute ring (DST009) and stay
    clean under lint_sharded_step; the Ulysses all_to_all path must
    lint clean too.  Miniature geometry — the pinned budget model
    (``ring_attention_fwd``) covers the full one."""
    import jax

    from . import shard_prop as sp
    from .shard_fixtures import ring_attention_program

    k = 4
    mesh = sp.MeshSpec({"sequence": k})
    findings = []
    for tag, with_grad in (("fwd", False), ("fwd+bwd", True)):
        fn, args = ring_attention_program(
            k=k, batch=1, t_global=32, heads=4, head_dim=8,
            causal=True, with_grad=with_grad)
        closed = jax.make_jaxpr(fn, axis_env=[("sequence", k)])(*args)
        subject = "parallel/ring_attention.py:%s" % tag
        findings += sp.lint_ring_schedule(closed, "sequence", k,
                                          subject=subject)
        findings += sp.lint_sharded_step(
            closed, mesh, data_axes=("sequence",),
            varying_invars=[0, 1, 2],
            shard_dims={i: {1: ("sequence",)} for i in range(3)},
            param_outvars=[], subject=subject)

    from .shard_fixtures import ulysses_attention_program
    for tag, with_grad in (("ulysses", False),
                           ("ulysses fwd+bwd", True)):
        fn, args = ulysses_attention_program(
            k=k, batch=1, t_global=32, heads=4, head_dim=8,
            causal=True, with_grad=with_grad)
        uclosed = jax.make_jaxpr(
            fn, axis_env=[("sequence", k)])(*args)
        findings += sp.lint_sharded_step(
            uclosed, mesh, data_axes=("sequence",),
            varying_invars=[0, 1, 2],
            shard_dims={i: {1: ("sequence",)} for i in range(3)},
            param_outvars=[],
            subject="parallel/ring_attention.py:%s" % tag)
    return filter_findings(findings, disable)


def lint_rule_docs(disable=()):
    """DOC001: every rule in RULES must have a row in the docs/analysis.md
    rule table — new rules (e.g. a source-pass addition) land in the docs
    in the same PR, enforced by ``--self-check``.  Skipped silently when
    the repo docs are not present (installed package)."""
    import os
    import re

    docs = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "docs", "analysis.md")
    if not os.path.isfile(docs):
        return []
    with open(docs) as f:
        documented = set(re.findall(r"^\|\s*([A-Z]{3,4}\d{3})\s*\|",
                                    f.read(), re.M))
    findings = [Finding("DOC001", rule,
                        "rule %s is registered but has no row in "
                        "docs/analysis.md" % rule)
                for rule in sorted(RULES)
                if rule not in documented and rule != "DOC001"]
    return filter_findings(findings, disable)
