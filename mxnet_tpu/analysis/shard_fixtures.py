"""Canonical sharded-step programs for the mxshard passes.

Three patterns cover the collective grammar of every planned parallelism
tier (ROADMAP items 1-2), each as a hand-spelled *per-replica* program
the analysis tier can trace hardware-free:

- **ZeRO-1 update** (arxiv 2004.13336): full forward/backward per
  replica, gradients reduce-scattered over the data axis, an optimizer
  whose state is 1/K-sized per rank, updated params all-gathered back.
  The memory proof: modeled peak HBM drops by optimizer-state-bytes x
  (1 - 1/K) vs the replicated twin — gated in STATIC_BUDGETS.json.
- **tensor-parallel matmul** (GSPMD, arxiv 1810.09868): a row-sharded
  weight contraction whose output is a partial-sum over the ``model``
  axis — the global-view propagation must *infer* the completing psum.
- **ring attention** (``parallel/ring_attention.py``): K/V chunks rotate
  over the ``sequence`` axis via scanned ``ppermute``; the schedule must
  match the ring formula (K hops x chunk bytes) — DST009's subject.

The module-level ``ZERO1_*`` flags are **mutation seams** for the
gate-kill tests (tests/test_shard_prop.py): flipping one from a
subprocess re-creates the classic bug (all-gather deleted -> DST007;
optimizer state kept replicated -> the ZeRO budget row blows COST001)
and the STATIC_BUDGETS gate must exit 2 naming the rule.  Production
code never touches them.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["ZERO1_GEOMETRY", "zero1_step_program", "zero1_state_bytes",
           "tp_matmul_program", "ring_attention_program",
           "ulysses_attention_program",
           "ZERO1_ALL_GATHER", "ZERO1_SHARD_STATE"]

# mutation seams (see module docstring) — flipped only by tests
ZERO1_ALL_GATHER = True      # False: the "forgot the all-gather" bug
ZERO1_SHARD_STATE = True     # False: replicated (full) optimizer state

# pinned trace geometry for the budgeted ZeRO model: a 3-layer MLP
# whose optimizer state (momentum) is large relative to activations, so
# the modeled 7/8 state saving is far outside the budget tolerance
ZERO1_GEOMETRY = {
    "batch": 64, "in_dim": 16, "hidden": (512, 128), "classes": 10,
    "momentum": 0.9, "lr": 0.1,
}


def _zero1_shapes(k):
    g = ZERO1_GEOMETRY
    dims = [(g["in_dim"], g["hidden"][0]), (g["hidden"][0],),
            (g["hidden"][0], g["hidden"][1]), (g["hidden"][1],),
            (g["hidden"][1], g["classes"]), (g["classes"],)]
    total = sum(int(_np.prod(s)) for s in dims)
    padded = -(-total // k) * k     # flat param vector, padded to K
    return dims, total, padded


def zero1_state_bytes(k=None):
    """float32 bytes of the FULL (replicated-twin) optimizer state —
    the quantity the ZeRO-1 proof says peak HBM drops by x (1 - 1/K)."""
    dims, total, padded = _zero1_shapes(k or 8)
    return padded * 4


def zero1_step_program(k, shard_state=None, all_gather=None):
    """(step_fn, example_args) — the per-replica ZeRO-1 spelling.

    ``step_fn(train_vals, m_state, x, y)`` returns ``(loss, new_vals,
    new_m)``.  With ``shard_state`` (default: the module seam) the
    momentum input/output is the rank's 1/K flat shard and grads are
    reduce-scattered; otherwise it is the replicated twin (full state,
    plain pmean) used as the HBM baseline.  ``all_gather=False`` spells
    the broken step that skips the covering gather (DST007's subject).
    Everything is shapes-only: callers trace with
    ``jax.make_jaxpr(axis_env=[("data", k)])``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    shard_state = ZERO1_SHARD_STATE if shard_state is None else shard_state
    all_gather = ZERO1_ALL_GATHER if all_gather is None else all_gather
    g = ZERO1_GEOMETRY
    dims, total, padded = _zero1_shapes(k)
    shard = padded // k
    mu, lr = g["momentum"], g["lr"]

    def loss_fn(tv, x, y):
        w1, b1, w2, b2, w3, b3 = tv
        h = jax.nn.relu(x @ w1 + b1)
        h = jax.nn.relu(h @ w2 + b2)
        logits = h @ w3 + b3
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return (lse - picked).mean()

    def unflatten(flat):
        out, off = [], 0
        for s in dims:
            n = int(_np.prod(s))
            out.append(flat[off:off + n].reshape(s))
            off += n
        return tuple(out)

    def step(train_vals, m_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(train_vals, x, y)
        flat_g = jnp.concatenate(
            [gr.ravel() for gr in grads]
            + [jnp.zeros((padded - total,), jnp.float32)])
        flat_w = jnp.concatenate(
            [v.ravel() for v in train_vals]
            + [jnp.zeros((padded - total,), jnp.float32)])
        if shard_state:
            # ZeRO-1: each rank owns 1/K of the flat (param, state)
            # space — reduce-scatter lands exactly the owned grad shard
            g_sh = lax.psum_scatter(flat_g, "data", scatter_dimension=0,
                                    tiled=True) / k
            idx = lax.axis_index("data")
            w_sh = lax.dynamic_slice(flat_w, (idx * shard,), (shard,))
            new_m = mu * m_state + g_sh
            new_w_sh = w_sh - lr * new_m
            if all_gather:
                new_flat = lax.all_gather(new_w_sh, "data", tiled=True)
            else:
                # the classic broken spelling: the rank's shard tiled
                # out as if it were the gathered whole
                new_flat = jnp.concatenate([new_w_sh] * k)
        else:
            # replicated twin: full-state baseline for the HBM proof
            g_mean = lax.pmean(flat_g, "data")
            new_m = mu * m_state + g_mean
            new_flat = flat_w - lr * new_m
        new_vals = unflatten(new_flat[:total])
        return lax.pmean(loss, "data"), new_vals, new_m

    state_n = shard if shard_state else padded
    args = (
        tuple(jax.ShapeDtypeStruct(s, jnp.float32) for s in dims),
        jax.ShapeDtypeStruct((state_n,), jnp.float32),
        jax.ShapeDtypeStruct((g["batch"], g["in_dim"]), jnp.float32),
        jax.ShapeDtypeStruct((g["batch"],), jnp.int32),
    )
    return step, args


def tp_matmul_program(batch=32, d_in=64, d_mid=128, d_out=32):
    """(fn, args, in_specs) — the tensor-parallel matmul pattern in the
    GLOBAL view: ``x @ W1`` with W1 column-sharded over ``model`` (free
    dim: no collective), then ``h @ W2`` with W2 row-sharded (contracted
    dim: the propagation must infer a partial-sum psum over ``model``)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    def fn(x, w1, w2):
        h = jax.nn.relu(x @ w1)
        return h @ w2

    args = (jax.ShapeDtypeStruct((batch, d_in), jnp.float32),
            jax.ShapeDtypeStruct((d_in, d_mid), jnp.float32),
            jax.ShapeDtypeStruct((d_mid, d_out), jnp.float32))
    in_specs = (PartitionSpec("data", None),      # batch over data
                PartitionSpec(None, "model"),     # W1 column-sharded
                PartitionSpec("model", None))     # W2 row-sharded
    return fn, args, in_specs


def ring_attention_program(k=8, batch=2, t_global=512, heads=4,
                           head_dim=32, causal=True, with_grad=True):
    """(fn, args) — the shipped ring attention's per-replica program at
    a pinned geometry: local (B, T/K, H, D) chunks over a declared
    ``sequence`` axis.  ``with_grad`` traces forward + backward (the
    dk/dv accumulators double the ring traffic: 6 ppermutes per hop
    total).  Trace with ``axis_env=[("sequence", k)]``."""
    import jax
    import jax.numpy as jnp

    from ..parallel.ring_attention import ring_attention

    t_local = t_global // k
    aval = jax.ShapeDtypeStruct((batch, t_local, heads, head_dim),
                                jnp.float32)

    if with_grad:
        def fn(q, kk, v):
            return jax.grad(
                lambda a, b, c: ring_attention(
                    a, b, c, "sequence", causal=causal).sum(),
                argnums=(0, 1, 2))(q, kk, v)
    else:
        def fn(q, kk, v):
            return ring_attention(q, kk, v, "sequence", causal=causal)
    return fn, (aval, aval, aval)


def ulysses_attention_program(k=8, batch=2, t_global=512, heads=8,
                              head_dim=32, causal=True, with_grad=True):
    """(fn, args) — the shipped Ulysses all-to-all attention's
    per-replica program at a pinned geometry (``heads % k == 0``):
    local (B, T/K, H, D) chunks swap sequence sharding for head
    sharding with one ``all_to_all`` per tensor, attend fully per
    local head group, and swap back.  ``with_grad`` traces forward +
    backward — the swap-back pair's VJPs are the inverse reshards, so
    the traced program carries exactly 8 all_to_alls whose wire bytes
    the ``ulysses_attention`` budget row pins.  Trace with
    ``axis_env=[("sequence", k)]``."""
    import jax
    import jax.numpy as jnp

    from ..parallel.ring_attention import ulysses_attention

    if heads % k:
        raise ValueError("ulysses needs heads %% k == 0 (got %d, %d)"
                         % (heads, k))
    t_local = t_global // k
    aval = jax.ShapeDtypeStruct((batch, t_local, heads, head_dim),
                                jnp.float32)

    if with_grad:
        def fn(q, kk, v):
            return jax.grad(
                lambda a, b, c: ulysses_attention(
                    a, b, c, "sequence", causal=causal).sum(),
                argnums=(0, 1, 2))(q, kk, v)
    else:
        def fn(q, kk, v):
            return ulysses_attention(q, kk, v, "sequence",
                                     causal=causal)
    return fn, (aval, aval, aval)
