"""mxfuse: mine the mxcost tape for memory-bound fusable chains.

TVM's operator fusion (PAPERS.md arxiv 1802.04799) groups injective /
broadcast / reduction-epilogue operators into one kernel so the
intermediates never round-trip through DRAM; XLA does the same invisibly
at compile time.  This pass is the *hardware-free planning* counterpart:
it walks the mxcost flat tape (whose per-eqn ``bytes_read`` /
``bytes_written`` are exactly the unfused upper bound a fused pass
elides), segments it into fusable **chains** — elementwise / broadcast /
cast / reduction-epilogue sequences connected by producer→consumer
dataflow, broken at dots, convs, collectives and layout-changing
movement (reshape/transpose/gather/...) — and ranks every chain by
modeled **bytes-saved-if-fused**:

    unfused = Σ over chain eqns (bytes_read + bytes_written)
    fused   = Σ unique external-input buffers + Σ unique chain outputs
    saved   = unfused − fused

(one fused pass reads each external buffer once and writes each
chain output once, however many chain eqns touch it — which is also why
a donated/in-place buffer is never double-counted).  The report is
byte-deterministic for a given tape, so the fusion plan can be gated
like every other modeled number.

The loop is closed the repo's own way: the top-ranked shipped chains
have real Pallas kernels (``ops/fused_optimizer.py`` — the fused ZeRO-1
/ replicated optimizer update — and the fused layernorm), those kernels
*declare* their cost with the cost pass (:data:`~.cost.KERNEL_COSTS`),
and the ``fused_optimizer_update`` budget model pins that the fused
spelling realizes the bytes this pass models (FUS001; the
``FUSED_OPTIMIZER`` seam kill).  :func:`lint_kernel_costs` is the
``--self-check`` sweep that keeps every shipped ``pallas_call``
annotated (COST005).

Entry points: ``python -m mxnet_tpu.analysis --cost --fusion``,
``Symbol.fusion_report()``, ``trainer.fusion_report()``; the doctor
names the fusion knob when a dominant dispatch/collective phase
coincides with a top chain covering more than
:data:`FUSION_HINT_MIN_PCT` of step bytes (docs/fusion.md).
"""
from __future__ import annotations

import ast
import glob
import os

from .cost import (TRANSCENDENTALS, _MOVEMENT, _COLLECTIVES, _AXIS_LOCAL,
                   _aval_bytes, build_tape, KERNEL_COSTS)
from .findings import Finding, filter_findings

__all__ = ["FUSION_HINT_MIN_PCT", "FusionChain", "FusionReport",
           "is_fusable", "segment_chains", "analyze_tape_fusion",
           "fusion_from_jaxpr", "fusion_from_fn", "fusion_for_symbol",
           "lint_kernel_costs", "pallas_kernels_used"]

# a top-ranked chain covering more than this share of the step's total
# HBM bytes makes the performance doctor name the fusion knob when
# dispatch / collective_or_ps dominates (CONTEXT_HINTS tag "fusable")
FUSION_HINT_MIN_PCT = 20.0

# cheap data-movement that fuses INTO a single pass (no relayout): a
# broadcast materializes nothing, a cast is one convert per element, a
# select is elementwise.  Everything else in cost._MOVEMENT (reshape,
# transpose, gather, concatenate, slicing, padding ...) changes layout
# or addressing and BREAKS a chain — a fused loop nest cannot stream
# through it with one index function.
_FUSABLE_MOVEMENT = frozenset({
    "broadcast_in_dim", "convert_element_type", "select_n", "copy",
    "stop_gradient", "squeeze", "expand_dims", "real", "imag",
})

# call-like / opaque primitives that can appear on the tape as connector
# or declared-cost ops: never chain members
_OPAQUE = frozenset({
    "pallas_call", "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "remat", "scan", "while", "cond",
})


def is_fusable(prim):
    """Can one fused memory pass absorb this primitive?  Elementwise
    arithmetic, transcendentals, casts, broadcasts and plain reductions
    (the epilogue class) fuse; dots, convs, collectives, layout-changing
    movement, scatters, sorts, windows and opaque calls break."""
    if prim in _FUSABLE_MOVEMENT:
        return True
    if prim in TRANSCENDENTALS:
        return True
    if prim in _COLLECTIVES or prim in _AXIS_LOCAL or prim in _OPAQUE:
        return False
    if prim in _MOVEMENT:        # the layout-changing remainder
        return False
    if prim in ("dot_general", "conv_general_dilated", "sort",
                "select_and_scatter_add"):
        return False
    if prim.startswith("reduce_window"):
        return False
    if prim.startswith("reduce_") or prim in ("argmax", "argmin"):
        return True              # reduction epilogue (and its broadcast
        # back into the chain — the normalization pattern)
    if prim.startswith("scatter") or prim.startswith("cum"):
        return False
    # default elementwise (add/mul/clamp/compare/...): one op per output
    return True


class FusionChain:
    """One fusable chain: contiguous dataflow-connected tape eqns that a
    single fused pass could execute with one read of every external
    input and one write of every chain output."""
    __slots__ = ("first_op", "op_indices", "prims", "kind", "scale",
                 "unfused_bytes", "fused_bytes", "bytes_saved",
                 "external_in_bytes", "external_out_bytes",
                 "pct_of_step_bytes")

    def __init__(self, first_op, op_indices, prims, kind, scale,
                 unfused_bytes, fused_bytes, external_in_bytes,
                 external_out_bytes, pct_of_step_bytes):
        self.first_op = first_op
        self.op_indices = op_indices
        self.prims = prims
        self.kind = kind
        self.scale = scale
        self.unfused_bytes = unfused_bytes
        self.fused_bytes = fused_bytes
        self.bytes_saved = unfused_bytes - fused_bytes
        self.external_in_bytes = external_in_bytes
        self.external_out_bytes = external_out_bytes
        self.pct_of_step_bytes = pct_of_step_bytes

    def as_dict(self):
        return {
            "first_op": int(self.first_op),
            "n_ops": len(self.op_indices),
            "prims": list(self.prims),
            "kind": self.kind,
            "scale": int(self.scale),
            "unfused_bytes": int(self.unfused_bytes),
            "fused_bytes": int(self.fused_bytes),
            "bytes_saved": int(self.bytes_saved),
            "external_in_bytes": int(self.external_in_bytes),
            "external_out_bytes": int(self.external_out_bytes),
            "pct_of_step_bytes": float(self.pct_of_step_bytes),
        }


def _chain_kind(prims):
    s = set(prims)
    reduces = any(p.startswith("reduce_") or p in ("argmax", "argmin")
                  for p in prims)
    if reduces and (s & {"rsqrt", "sqrt"}):
        return "normalization"
    if reduces:
        return "reduction_epilogue"
    if s <= _FUSABLE_MOVEMENT:
        return "cast"
    return "elementwise"


def segment_chains(tape):
    """Union-find over the tape's fusable eqns along producer→consumer
    edges (same ``scale`` only — a chain never crosses a scan boundary).
    Returns chains as sorted lists of op indices, ≥ 2 ops each, in
    first-op order (deterministic)."""
    n = len(tape.ops)
    fusable = [is_fusable(op.prim) for op in tape.ops]
    parent = list(range(n))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            # deterministic: smaller index wins the root
            if ra < rb:
                parent[rb] = ra
            else:
                parent[ra] = rb

    producer = {}
    for idx, op in enumerate(tape.ops):
        if not fusable[idx]:
            continue
        for oid in op.out_ids:
            producer[oid] = idx
    for idx, op in enumerate(tape.ops):
        if not fusable[idx]:
            continue
        for iid in op.in_ids:
            j = producer.get(iid)
            if j is not None and j != idx \
                    and tape.ops[j].scale == op.scale:
                union(idx, j)
    groups = {}
    for idx in range(n):
        if fusable[idx]:
            groups.setdefault(find(idx), []).append(idx)
    return [sorted(g) for _, g in sorted(groups.items())
            if len(g) >= 2]


def _chain_stats(tape, idxs, total_bytes):
    idx_set = set(idxs)
    ops = [tape.ops[i] for i in idxs]
    scale = ops[0].scale
    produced = set()
    for op in ops:
        produced.update(op.out_ids)
    ext_in = set()
    for op in ops:
        for iid in op.in_ids:
            if iid not in produced and iid not in tape.literal_ids:
                ext_in.add(iid)
    prog_outs = set(tape.outvar_ids)
    consumed_outside = set()
    for k, op in enumerate(tape.ops):
        if k in idx_set:
            continue
        for iid in op.in_ids:
            if iid in produced:
                consumed_outside.add(iid)
    ext_out = {oid for oid in produced
               if oid in consumed_outside or oid in prog_outs}
    # unique buffers, counted ONCE each (chain ops re-reading a donated
    # or shared operand do not double-bill the fused pass)
    in_bytes = sum(_aval_bytes(tape.avals[i]) for i in sorted(ext_in))
    out_bytes = sum(_aval_bytes(tape.avals[i]) for i in sorted(ext_out))
    unfused = sum(op.bytes_read + op.bytes_written for op in ops)
    fused = (in_bytes + out_bytes) * scale
    if fused > unfused:
        fused = unfused          # a chain can never cost more fused
    prims = [op.prim for op in ops]
    pct = round(100.0 * (unfused - fused) / total_bytes, 4) \
        if total_bytes else 0.0
    return FusionChain(
        first_op=idxs[0], op_indices=list(idxs), prims=prims,
        kind=_chain_kind(prims), scale=scale, unfused_bytes=unfused,
        fused_bytes=fused, external_in_bytes=in_bytes * scale,
        external_out_bytes=out_bytes * scale, pct_of_step_bytes=pct)


class FusionReport:
    """Deterministic ranking of a program's fusable chains by modeled
    bytes-saved-if-fused.  ``as_dict()`` is the stable JSON surface
    (docs/fusion.md); chains are ranked ``(-bytes_saved, first_op)``."""

    def __init__(self, chains, total_tape_bytes, n_eqns):
        self.chains = sorted(chains,
                             key=lambda c: (-c.bytes_saved, c.first_op))
        self.total_tape_bytes = int(total_tape_bytes)
        self.n_eqns = int(n_eqns)
        self.total_bytes_saved = sum(c.bytes_saved for c in self.chains)

    @property
    def bytes_saved_pct(self):
        if not self.total_tape_bytes:
            return 0.0
        return round(100.0 * self.total_bytes_saved
                     / self.total_tape_bytes, 4)

    @property
    def top_chain(self):
        return self.chains[0] if self.chains else None

    @property
    def top_chain_pct(self):
        """The top chain's share of the program's total HBM bytes —
        what the doctor hint thresholds on (FUSION_HINT_MIN_PCT)."""
        top = self.top_chain
        if top is None or not self.total_tape_bytes:
            return 0.0
        return round(100.0 * top.unfused_bytes / self.total_tape_bytes,
                     4)

    def as_dict(self):
        return {
            "n_eqns": self.n_eqns,
            "total_tape_bytes": self.total_tape_bytes,
            "total_bytes_saved": int(self.total_bytes_saved),
            "bytes_saved_pct": self.bytes_saved_pct,
            "top_chain_pct": self.top_chain_pct,
            "n_chains": len(self.chains),
            "chains": [c.as_dict() for c in self.chains],
        }

    def render(self, title="mxfuse"):
        lines = ["%s: %d chain(s) over %d eqns, %.2f MiB saved-if-fused "
                 "(%.1f%% of %.2f MiB tape bytes)"
                 % (title, len(self.chains), self.n_eqns,
                    self.total_bytes_saved / (1 << 20),
                    self.bytes_saved_pct,
                    self.total_tape_bytes / (1 << 20))]
        for rank, c in enumerate(self.chains[:8]):
            prims = ",".join(c.prims[:6])
            if len(c.prims) > 6:
                prims += ",…(%d)" % len(c.prims)
            lines.append(
                "  #%-2d %-18s %4d ops  saves %10d B (%.1f%% of step)"
                "  [%s]" % (rank + 1, c.kind, len(c.op_indices),
                            c.bytes_saved, c.pct_of_step_bytes, prims))
        return "\n".join(lines)


def analyze_tape_fusion(tape):
    """FusionReport for a built Tape."""
    total = sum(op.bytes_read + op.bytes_written for op in tape.ops)
    chains = [_chain_stats(tape, idxs, total)
              for idxs in segment_chains(tape)]
    chains = [c for c in chains if c.bytes_saved > 0]
    return FusionReport(chains, total, len(tape.ops))


def fusion_from_jaxpr(closed_jaxpr, axis_sizes=None):
    """FusionReport for a ClosedJaxpr (tape built exactly like the cost
    pass: inlined through pjit/remat/scan; declared-cost pallas kernels
    appear as single opaque ops and never join chains)."""
    return analyze_tape_fusion(build_tape(closed_jaxpr,
                                          axis_sizes=axis_sizes))


def fusion_from_fn(fn, *args, axis_env=None, axis_sizes=None, **kwargs):
    """Trace ``fn`` with ``jax.make_jaxpr`` (no execution) and analyze."""
    import jax

    closed = jax.make_jaxpr(fn, axis_env=axis_env)(*args, **kwargs)
    sizes = dict(axis_env or [])
    sizes.update(axis_sizes or {})
    return fusion_from_jaxpr(closed, axis_sizes=sizes)


def fusion_for_symbol(symbol, shapes, type_dict=None, train=False):
    """FusionReport for a Symbol's forward program (the
    ``Symbol.fusion_report()`` implementation; same tracing contract as
    ``analyze_symbol``).  Returns None when the graph does not trace."""
    from .cost import symbol_closed_jaxpr

    traced = symbol_closed_jaxpr(symbol, shapes, type_dict=type_dict,
                                 train=train)
    if traced is None:
        return None
    closed, _, _ = traced
    return fusion_from_jaxpr(closed)


# ---------------------------------------------------------------------------
# the declared-cost lint: every shipped pallas_call must price itself
# ---------------------------------------------------------------------------
def pallas_kernels_used(root=None):
    """AST sweep of ``mxnet_tpu/ops/*.py`` for ``pallas_call(...)``
    call sites, resolving each one's kernel function name: a direct
    ``Name``, a ``functools.partial(name, ...)`` argument, or a local
    variable assigned from either inside the enclosing function.
    Returns ``(kernels, dynamic)``: ``kernels`` maps kernel name →
    ``file:line`` use sites; ``dynamic`` lists call sites whose kernel
    could not be resolved (findings too — an unresolvable kernel can
    never be checked against the registry)."""
    root = root or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ops")
    kernels, dynamic = {}, []

    def _partial_target(node):
        """name for functools.partial(<name>, ...) / partial(<name>,...)"""
        if not isinstance(node, ast.Call) or not node.args:
            return None
        fn = node.func
        callee = fn.attr if isinstance(fn, ast.Attribute) else \
            getattr(fn, "id", None)
        if callee != "partial":
            return None
        first = node.args[0]
        if isinstance(first, ast.Name):
            return first.id
        if isinstance(first, ast.Attribute):
            return first.attr
        return None

    def _local_map(fnode):
        """var name -> kernel fn name for partial assignments."""
        local = {}
        for sub in ast.walk(fnode):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                target = _partial_target(sub.value)
                if target is None and isinstance(sub.value, ast.Name):
                    target = local.get(sub.value.id)
                if target:
                    local[sub.targets[0].id] = target
        return local

    for path in sorted(glob.glob(os.path.join(root, "*.py"))):
        if os.path.basename(path) == "generated_kernels.py":
            # mxgen kernels are exec'd from generated source — the AST
            # sweep cannot see them; the registry-driven COST006 check
            # in lint_kernel_costs covers that module instead
            continue
        rel = os.path.join("ops", os.path.basename(path))
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        fdefs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        # (helper fn name, kernel param index, where): pallas_call on a
        # parameter — resolved one hop up through the helper's callers
        deferred = []
        for fnode in fdefs:
            local = _local_map(fnode)
            params = [a.arg for a in fnode.args.args]
            for sub in ast.walk(fnode):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                callee = fn.attr if isinstance(fn, ast.Attribute) else \
                    getattr(fn, "id", None)
                if callee != "pallas_call" or not sub.args:
                    continue
                where = "%s:%d" % (rel, sub.lineno)
                first = sub.args[0]
                name = None
                if isinstance(first, ast.Name):
                    name = local.get(first.id)
                    if name is None and first.id in params:
                        deferred.append((fnode.name,
                                         params.index(first.id), where))
                        continue
                    name = name or first.id
                elif isinstance(first, ast.Attribute):
                    name = first.attr
                else:
                    name = _partial_target(first)
                if name:
                    kernels.setdefault(name, []).append(where)
                else:
                    dynamic.append(where)
        for helper, argpos, where in deferred:
            resolved_any = False
            for fnode in fdefs:
                local = _local_map(fnode)
                for sub in ast.walk(fnode):
                    if not isinstance(sub, ast.Call):
                        continue
                    fn = sub.func
                    callee = fn.attr if isinstance(fn, ast.Attribute) \
                        else getattr(fn, "id", None)
                    if callee != helper or len(sub.args) <= argpos:
                        continue
                    arg = sub.args[argpos]
                    name = None
                    if isinstance(arg, ast.Name):
                        name = local.get(arg.id, arg.id)
                    else:
                        name = _partial_target(arg)
                    if name:
                        kernels.setdefault(name, []).append(
                            "%s (via %s:%d)" % (where, helper,
                                                sub.lineno))
                        resolved_any = True
                    else:
                        dynamic.append("%s (caller %s:%d)"
                                       % (where, helper, sub.lineno))
            if not resolved_any:
                dynamic.append(where)
    return kernels, dynamic


def lint_kernel_costs(disable=(), root=None):
    """COST005 sweep (``--self-check``): every ``pallas_call`` in the
    shipped op sources must name a kernel with a registered
    ``declare_kernel_cost`` model — otherwise the cost pass prices it
    off a once-per-trace body walk and every byte/FLOP budget the
    kernel participates in silently lies."""
    # importing the op modules runs their declare_kernel_cost
    # registrations; the AST names below are checked against the result
    from ..ops import pallas_kernels as _pk          # noqa: F401
    from ..ops import fused_optimizer as _fo         # noqa: F401

    kernels, dynamic = pallas_kernels_used(root)
    findings = []
    for name in sorted(set(kernels) - set(KERNEL_COSTS)):
        findings.append(Finding(
            "COST005", name,
            "pallas_call kernel %r (used at %s) has no "
            "declare_kernel_cost model — the cost pass prices it off a "
            "once-per-trace body walk; declare its flops/bytes so the "
            "budget gate stops lying about it"
            % (name, ", ".join(kernels[name]))))
    for where in dynamic:
        findings.append(Finding(
            "COST005", where,
            "pallas_call whose kernel argument cannot be resolved to a "
            "function name — the declared-cost registry cannot be "
            "checked for it; pass the kernel fn (or a functools."
            "partial of it) directly"))
    # generated kernels (ops/generated_kernels.py) are exec'd source the
    # AST sweep above cannot see: check the REGISTRY instead — a mxgen
    # kernel that lost its auto-declared cost entry is a gate error
    # (COST006), not a silent skip
    from ..ops import generated_kernels as _gen
    for name in sorted(set(_gen.GENERATED_KERNELS) - set(KERNEL_COSTS)):
        findings.append(Finding(
            "COST006", name,
            "generated kernel %r is in GENERATED_KERNELS but has no "
            "KERNEL_COSTS entry — register_generated auto-declares one; "
            "something deleted or bypassed it, so the cost pass would "
            "price the kernel off the once-per-trace body walk"
            % (name,)))
    return filter_findings(findings, disable)
