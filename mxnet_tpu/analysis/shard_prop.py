"""mxshard: whole-program static sharding propagation over the mxcost tape.

GSPMD (PAPERS.md arxiv 1810.09868) partitions a whole XLA program from a
handful of sharding annotations: specs propagate through every op,
resharding is inserted where operands disagree, and contracted sharded
dims become partial-sums that an all-reduce later completes.  With the
live TPU signal dead, this module does the same propagation *statically*
— a mesh is a name→size declaration (``MeshSpec``), no devices — so the
repo can prove a ZeRO/tensor-parallel/sequence-parallel step's collective
schedule and memory story on the 1-core CI host.

Two complementary views over the same inlined tape (:mod:`.cost`):

- **global view** (:func:`propagate`): the program traced WITHOUT an
  axis env; inputs carry factored sharding specs (``ShardSpec``, built
  from ``PartitionSpec``); specs propagate GSPMD-style through every
  eqn.  Where operands disagree a **forced reshard** is recorded (the
  hidden ``all_to_all`` class — DST010); where a contracted sharded dim
  creates a partial-sum an **inferred psum** is scheduled (the
  tensor-parallel matmul reduction GSPMD would insert).  The result is a
  modeled collective schedule (op, axes, bytes, ring cost) for a program
  that never spelled a collective.

- **per-replica view** (:func:`collective_schedule`,
  :func:`lint_sharded_step`): the program traced WITH ``axis_env`` — the
  ``shard_map`` / ``_build_replica_step`` spelling where collectives are
  explicit and shapes are local shards.  The schedule prices every
  explicit collective with the multi-axis ring formulas
  (:func:`.cost.ring_bytes_per_axis`); a per-axis variance propagation
  distinguishing *content* variance (batch-derived: a different value
  per rank), *layout* variance (a different **piece** per rank: sharded
  params, scattered optimizer shards) and pending *partial sums* proves
  the mixed-axis step rules DST006–DST010 (docs/analysis.md).

Both views walk the scan body once (costs are scaled by trip count;
variance reaches a fixpoint for every shipped pattern in one pass) and
treat ``psum`` of a literal (the ``lax.psum(1, axis)`` axis-size idiom)
as axis arithmetic, not a collective.
"""
from __future__ import annotations

from .cost import (_AXIS_LOCAL, _COLLECTIVES, _aval_bytes, _axis_names,
                   build_tape, ring_bytes_per_axis, unpriced_findings)
from .findings import Finding, filter_findings

__all__ = ["MeshSpec", "ShardSpec", "CollectiveEvent", "ReshardEvent",
           "ShardReport", "propagate", "collective_schedule",
           "lint_sharded_step", "lint_ring_schedule",
           "lint_global_sharding", "shard_summary"]

# collectives that reduce (sum/min/max) across the named axes
_REDUCING = frozenset({"psum", "pmax", "pmin"})


def _reduce_dtype_findings(op, tape, subject):
    """Tightened DST004 over one wire-reducing collective (psum or
    reduce_scatter): sub-f32 float operands are an ERROR — shared with
    ``dist_lint``'s replicated-spelling pass."""
    import numpy as _np

    from .findings import ERROR as _ERR
    out = []
    for i in op.in_ids:
        aval = tape.avals.get(i)
        dt = getattr(aval, "dtype", None)
        if dt is None:
            continue
        try:
            import jax.numpy as jnp
            if not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
                continue
        except TypeError:
            continue
        if _np.dtype(dt).itemsize < 4:
            out.append(Finding(
                "DST004", subject,
                "%s over %r reduces %s on the wire: a ring reduction "
                "accumulates one rounding per hop, so gradients must "
                "be cast to float32 BEFORE the collective and only "
                "narrowed after (the mixed-precision contract, "
                "docs/precision.md)"
                % (op.prim, sorted(op.axes), _np.dtype(dt).name),
                severity=_ERR))
    return out


class MeshSpec:
    """A mesh as pure declaration: ordered ``{axis_name: size}``.

    No devices are ever constructed — the whole point is analyzing an
    8-way (or 2×4×… ) mesh from a 1-core host.  Accepts a dict, a list
    of pairs, or a live ``jax.sharding.Mesh`` (sizes are read off it).
    """

    def __init__(self, axes):
        if hasattr(axes, "axis_names") and hasattr(axes, "devices"):
            axes = dict(zip(axes.axis_names, axes.devices.shape))
        if isinstance(axes, dict):
            items = list(axes.items())
        else:
            items = [(a, s) for a, s in axes]
        self.axes = {str(a): int(s) for a, s in items}

    def size(self, axis):
        return self.axes.get(axis, 1)

    def group_size(self, axes):
        n = 1
        for a in axes:
            n *= self.size(a)
        return n

    def __contains__(self, axis):
        return axis in self.axes

    def names(self):
        return tuple(self.axes)

    def as_dict(self):
        return {a: int(s) for a, s in self.axes.items()}

    def __repr__(self):
        return "MeshSpec(%r)" % (self.axes,)


class ShardSpec:
    """Factored sharding of one value: per-dim mesh axes + partial axes.

    ``dims[d]`` is the tuple of mesh axes dim ``d`` is split over
    (GSPMD's tiled assignment); ``partial`` is the set of axes over
    which the value is a pending partial-sum (each member of the axis
    holds an addend; a ``psum`` over it completes the value).
    """
    __slots__ = ("dims", "partial")

    def __init__(self, dims, partial=()):
        self.dims = tuple(tuple(d) for d in dims)
        self.partial = frozenset(partial)

    @classmethod
    def replicated(cls, rank):
        return cls(((),) * rank)

    @classmethod
    def from_partition_spec(cls, spec, rank):
        """From a ``jax.sharding.PartitionSpec`` (or tuple / None)."""
        if spec is None:
            return cls.replicated(rank)
        if isinstance(spec, ShardSpec):
            return spec
        entries = list(tuple(spec))
        dims = []
        for d in range(rank):
            e = entries[d] if d < len(entries) else None
            if e is None:
                dims.append(())
            elif isinstance(e, str):
                dims.append((e,))
            else:
                dims.append(tuple(e))
        return cls(dims)

    def axes(self):
        """Every mesh axis this value is tiled over."""
        return frozenset(a for d in self.dims for a in d)

    def shard_factor(self, mesh):
        n = 1
        for d in self.dims:
            for a in d:
                n *= mesh.size(a)
        return n

    def local_bytes(self, aval, mesh):
        """Bytes of one device's tile of a global ``aval``."""
        return _aval_bytes(aval) // max(self.shard_factor(mesh), 1)

    def with_rank(self, rank):
        if len(self.dims) == rank:
            return self
        dims = (self.dims + ((),) * rank)[:rank]
        return ShardSpec(dims, self.partial)

    def as_tuple(self):
        return (self.dims, tuple(sorted(self.partial)))

    def as_dict(self):
        return {"dims": [list(d) for d in self.dims],
                "partial": sorted(self.partial)}

    def __eq__(self, other):
        return (isinstance(other, ShardSpec) and self.dims == other.dims
                and self.partial == other.partial)

    def __hash__(self):
        return hash(self.as_tuple())

    def __repr__(self):
        return "ShardSpec(%r%s)" % (
            self.dims, ", partial=%r" % sorted(self.partial)
            if self.partial else "")


class CollectiveEvent:
    """One modeled collective: explicit (from the tape) or inferred
    (GSPMD would insert it)."""
    __slots__ = ("index", "prim", "axes", "payload_bytes", "wire_bytes",
                 "per_axis", "scale", "inferred", "note")

    def __init__(self, index, prim, axes, payload_bytes, per_axis,
                 scale=1, inferred=False, note=""):
        self.index = int(index)
        self.prim = prim
        self.axes = tuple(axes)
        self.payload_bytes = int(payload_bytes)
        self.per_axis = {a: int(b) for a, b in per_axis.items()}
        self.wire_bytes = int(sum(self.per_axis.values()))
        self.scale = int(scale)
        self.inferred = bool(inferred)
        self.note = note

    def as_dict(self):
        return {"index": self.index, "prim": self.prim,
                "axes": list(self.axes),
                "payload_bytes": self.payload_bytes,
                "wire_bytes": self.wire_bytes,
                "per_axis": {a: b for a, b in sorted(self.per_axis.items())},
                "scale": self.scale, "inferred": self.inferred,
                "note": self.note}


class ReshardEvent:
    """A forced layout change: the operand's sharding disagreed with
    what the consuming eqn needed — GSPMD would insert a hidden
    collective here (DST010)."""
    __slots__ = ("index", "prim", "kind", "axes", "wire_bytes", "note")

    def __init__(self, index, prim, kind, axes, wire_bytes, note=""):
        self.index = int(index)
        self.prim = prim
        self.kind = kind          # "all_to_all" | "all_gather"
        self.axes = tuple(axes)
        self.wire_bytes = int(wire_bytes)
        self.note = note

    def as_dict(self):
        return {"index": self.index, "prim": self.prim, "kind": self.kind,
                "axes": list(self.axes), "wire_bytes": self.wire_bytes,
                "note": self.note}


class ShardReport:
    """Deterministic shard-propagation summary of one program: the
    modeled collective schedule (explicit + inferred), forced reshards,
    per-axis wire bytes and the input/output factored specs.  The
    ``extras`` dict carries model-specific derived numbers (e.g. the
    ZeRO-1 HBM proof) into the ``--json`` ``shard`` section."""

    def __init__(self, mesh, in_specs=(), out_specs=(), schedule=(),
                 reshards=(), unpriced=(), extras=None):
        self.mesh = mesh
        self.in_specs = list(in_specs)
        self.out_specs = list(out_specs)
        self.schedule = list(schedule)
        self.reshards = list(reshards)
        self.unpriced = list(unpriced)
        self.extras = dict(extras or {})

    @property
    def collective_bytes_per_axis(self):
        out = {}
        for ev in self.schedule:
            for a, b in ev.per_axis.items():
                out[a] = out.get(a, 0) + b
        return out

    @property
    def collective_bytes(self):
        return sum(self.collective_bytes_per_axis.values())

    @property
    def reshard_bytes(self):
        return sum(ev.wire_bytes for ev in self.reshards)

    def as_dict(self):
        return {
            "mesh": self.mesh.as_dict(),
            "in_specs": [s.as_dict() if isinstance(s, ShardSpec) else s
                         for s in self.in_specs],
            "out_specs": [s.as_dict() if isinstance(s, ShardSpec) else s
                          for s in self.out_specs],
            "schedule": [ev.as_dict() for ev in self.schedule],
            "reshards": [ev.as_dict() for ev in self.reshards],
            "collective_bytes": int(self.collective_bytes),
            "collective_bytes_per_axis": {
                a: int(b) for a, b in
                sorted(self.collective_bytes_per_axis.items())},
            "reshard_bytes": int(self.reshard_bytes),
            "n_collectives": len(self.schedule),
            "unpriced_collectives": [
                {"prim": p, "axis": a, "reason": r}
                for p, a, r in sorted(set(self.unpriced))],
            "extras": dict(sorted(self.extras.items())),
        }

    def render(self, title="mxshard"):
        d = self.as_dict()
        lines = ["%s: mesh %s — %d collective(s), %.2f MiB wire, "
                 "%d reshard(s)" % (
                     title, d["mesh"], d["n_collectives"],
                     d["collective_bytes"] / (1 << 20),
                     len(d["reshards"]))]
        for ev in self.schedule[:16]:
            lines.append("  [%4d] %-16s%s over %-18s %10d B x%d%s" % (
                ev.index, ev.prim, "*" if ev.inferred else " ",
                "x".join(ev.axes) or "-", ev.wire_bytes, ev.scale,
                (" (%s)" % ev.note) if ev.note else ""))
        if len(self.schedule) > 16:
            lines.append("  ... %d more" % (len(self.schedule) - 16))
        for ev in self.reshards:
            lines.append("  [%4d] RESHARD %s at %s over %s: %d B" % (
                ev.index, ev.kind, ev.prim, "x".join(ev.axes) or "-",
                ev.wire_bytes))
        for p, a, r in sorted(set(self.unpriced)):
            lines.append("  UNPRICED %s over %r (%s)" % (p, a, r))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# per-replica view: explicit collectives, variance propagation
# ---------------------------------------------------------------------------
class _VState:
    """Per-value variance state in the per-replica (local-shard) view.

    ``content``: axes along which the VALUE differs per rank (batch
    shards and everything derived from them).  ``dims[d]``: axes along
    which dim ``d`` holds a different PIECE per rank (layout sharding).
    ``partial``: pending partial-sum axes.  ``reduced``: axes a
    reducing collective already covered on this chain (DST008 feed).
    ``scattered``: axes whose layout came from an in-step
    ``reduce_scatter`` that no ``all_gather`` has covered yet (DST007).
    """
    __slots__ = ("content", "dims", "partial", "reduced", "scattered")

    def __init__(self, rank=0, content=(), dims=None, partial=(),
                 reduced=(), scattered=()):
        self.content = frozenset(content)
        self.dims = tuple(frozenset(d) for d in (
            dims if dims is not None else ((),) * rank))
        self.partial = frozenset(partial)
        self.reduced = frozenset(reduced)
        self.scattered = frozenset(scattered)

    def layout(self):
        return frozenset(a for d in self.dims for a in d)

    def clone(self, **kw):
        out = _VState()
        for slot in _VState.__slots__:
            setattr(out, slot, kw.get(slot, getattr(self, slot)))
        return out


def _union_state(states, out_rank):
    content = frozenset().union(*(s.content for s in states)) \
        if states else frozenset()
    partial = frozenset().union(*(s.partial for s in states)) \
        if states else frozenset()
    reduced = frozenset().union(*(s.reduced for s in states)) \
        if states else frozenset()
    scattered = frozenset().union(*(s.scattered for s in states)) \
        if states else frozenset()
    # dims merge PER DIMENSION across same-rank operands: a dim keeps
    # its layout when every operand that declares one agrees (an empty
    # dim is broadcast/replicated along it — elementwise ops preserve
    # the sharded operand's layout); conflicting layouts degrade that
    # dim to unknown.  Rank changes the handler didn't map degrade the
    # whole layout — value-level sets survive, so the error rules stay
    # sound either way.
    cands = [s for s in states if len(s.dims) == out_rank]
    if not cands:
        dims = tuple(frozenset() for _ in range(out_rank))
    else:
        dims = []
        for d in range(out_rank):
            declared = {s.dims[d] for s in cands if s.dims[d]}
            dims.append(declared.pop() if len(declared) == 1
                        else frozenset())
        dims = tuple(dims)
    return _VState(content=content, dims=dims, partial=partial,
                   reduced=reduced, scattered=scattered)


def _rank_of(aval):
    return len(getattr(aval, "shape", ()))


def _dot_contracted_axes(op, states):
    (lc, rc), _ = op.params["dimension_numbers"]
    axes = set()
    for side, cdims in ((0, lc), (1, rc)):
        if side < len(states):
            s = states[side]
            for d in cdims:
                if d < len(s.dims):
                    axes |= s.dims[d]
    return frozenset(axes)


def _replica_collect(tape, mesh, init_states, data_axes, on_reduce=None):
    """Walk the tape propagating ``_VState``; calls ``on_reduce(op,
    in_state, axes)`` at every reducing collective (for the lint rules).
    Returns ``{var_id: _VState}``."""
    env = {}

    def state_of(i):
        if i in env:
            return env[i]
        return _VState(rank=_rank_of(tape.avals.get(i)))

    for i, st in init_states.items():
        env[i] = st

    for t, op in enumerate(tape.ops):
        in_states = [state_of(i) for i in op.in_ids]
        out_rank = _rank_of(tape.avals.get(op.out_ids[0])) \
            if op.out_ids else 0
        merged = _union_state(in_states, out_rank)
        axes = frozenset(a for a in op.axes)
        all_literal = all(i in tape.literal_ids for i in op.in_ids)

        if op.prim in _REDUCING and axes and not all_literal:
            if on_reduce is not None:
                on_reduce(t, op, merged, axes)
            new = merged.clone(
                content=merged.content - axes,
                partial=merged.partial - axes,
                reduced=merged.reduced | axes)
        elif op.prim == "reduce_scatter" and axes:
            if on_reduce is not None:
                on_reduce(t, op, merged, axes)
            d = int(op.params.get("scatter_dimension", 0))
            dims = list(merged.dims) if len(merged.dims) == out_rank \
                else [frozenset()] * out_rank
            if d < len(dims):
                dims[d] = dims[d] | axes
            new = merged.clone(
                content=merged.content - axes,
                partial=merged.partial - axes,
                reduced=merged.reduced | axes,
                scattered=merged.scattered | axes,
                dims=tuple(dims))
        elif op.prim == "all_gather" and axes:
            dims = tuple(d - axes for d in merged.dims) \
                if len(merged.dims) == out_rank \
                else tuple(frozenset() for _ in range(out_rank))
            new = merged.clone(
                content=merged.content - axes,
                scattered=merged.scattered - axes,
                dims=dims)
        elif op.prim == "all_to_all" and axes:
            split = op.params.get("split_axis")
            concat = op.params.get("concat_axis")
            dims = list(merged.dims) if len(merged.dims) == out_rank \
                else [frozenset()] * out_rank
            if split is not None and split < len(dims):
                dims[split] = dims[split] | axes
            if concat is not None and concat < len(dims):
                dims[concat] = dims[concat] - axes
            new = merged.clone(dims=tuple(dims))
        elif op.prim == "ppermute":
            # content rotates among ranks: still a different value per
            # rank — every variance survives
            new = merged
        elif op.prim == "pbroadcast" and axes:
            new = merged.clone(content=merged.content - axes,
                               scattered=merged.scattered - axes,
                               dims=tuple(d - axes for d in merged.dims))
        elif op.prim == "axis_index":
            new = _VState(rank=out_rank, content=axes)
        elif op.prim == "dot_general":
            # mirror the global view: batch/free layout dims map onto
            # the output, a contracted layout-sharded dim becomes a
            # pending partial-sum (each rank holds an addend — the
            # row-parallel matmul whose completing psum DST rules watch)
            contracted = _dot_contracted_axes(op, in_states)
            lhs = in_states[0] if in_states else _VState()
            rhs = in_states[1] if len(in_states) > 1 else _VState()
            (lc, rc), (lb, rb) = op.params["dimension_numbers"]
            lhs_ok = len(lhs.dims) == _rank_of(
                tape.avals.get(op.in_ids[0])) if op.in_ids else False
            rhs_ok = len(rhs.dims) == _rank_of(
                tape.avals.get(op.in_ids[1])) \
                if len(op.in_ids) > 1 else False
            if lhs_ok and rhs_ok:
                lfree = [d for d in range(len(lhs.dims))
                         if d not in set(lc) | set(lb)]
                rfree = [d for d in range(len(rhs.dims))
                         if d not in set(rc) | set(rb)]
                dims = [lhs.dims[d] for d in lb] \
                    + [lhs.dims[d] for d in lfree] \
                    + [rhs.dims[d] for d in rfree]
                dims = (dims + [frozenset()] * out_rank)[:out_rank]
            else:
                dims = list(merged.dims)
            new = merged.clone(partial=merged.partial | contracted,
                               dims=tuple(frozenset(d) for d in dims))
        elif op.prim.startswith("reduce_") and "axes" in op.params \
                and op.prim not in _COLLECTIVES and in_states:
            # a plain reduce over a layout-sharded dim leaves each rank
            # holding its shard's partial result — a pending partial-sum
            # the completing pmax/psum (vocab-parallel logsumexp)
            # resolves; non-reduced dims keep their layout
            src = in_states[0]
            red = set(op.params["axes"])
            partial = set(merged.partial)
            dims = []
            if op.in_ids and len(src.dims) == _rank_of(
                    tape.avals.get(op.in_ids[0])):
                for d, axs in enumerate(src.dims):
                    if d in red:
                        partial |= set(axs)
                    else:
                        dims.append(axs)
            if len(dims) != out_rank:
                dims = [frozenset()] * out_rank
            new = merged.clone(dims=tuple(dims),
                               partial=frozenset(partial))
        elif op.prim == "transpose" and in_states:
            src = in_states[0]
            perm = op.params.get("permutation", ())
            if len(src.dims) == len(perm):
                new = merged.clone(dims=tuple(
                    src.dims[p] for p in perm))
            else:
                new = merged
        elif op.prim == "squeeze" and in_states:
            # the stage-stacked parameter access pattern
            # (``blk_wq[j]`` -> slice + squeeze of the leading pipe
            # dim): surviving dims keep their layout, or the model-axis
            # sharding of every stacked layer would degrade to unknown
            # and the row-parallel psums would look like duplicate
            # reductions (DST008)
            src = in_states[0]
            sq = set(op.params.get("dimensions", ()))
            dims = tuple(axs for d, axs in enumerate(src.dims)
                         if d not in sq)
            if len(src.dims) == _rank_of(tape.avals.get(op.in_ids[0])) \
                    and len(dims) == out_rank:
                new = merged.clone(dims=dims)
            else:
                new = merged
        elif op.prim == "broadcast_in_dim" and in_states:
            src = in_states[0]
            bdims = op.params.get("broadcast_dimensions", ())
            dims = [frozenset()] * out_rank
            if len(src.dims) == len(bdims):
                for sd, od in enumerate(bdims):
                    if od < out_rank:
                        dims[od] = src.dims[sd]
            new = merged.clone(dims=tuple(dims))
        elif op.prim == "reshape" and in_states:
            src = in_states[0]
            src_shape = getattr(tape.avals.get(op.in_ids[0]), "shape",
                                ()) if op.in_ids else ()
            dst_shape = getattr(tape.avals.get(op.out_ids[0]), "shape",
                                ()) if op.out_ids else ()
            dims = [frozenset()] * out_rank
            if len(src.dims) == len(src_shape):
                dmap = _reshape_dim_map(src_shape, dst_shape)
                for sd, od in dmap.items():
                    if od < out_rank:
                        dims[od] = src.dims[sd]
            new = merged.clone(dims=tuple(dims))
        else:
            new = merged
        for o in op.out_ids:
            env[o] = new.clone() if len(op.out_ids) > 1 else new
    return env


def collective_schedule(closed_jaxpr, mesh, subject="<program>"):
    """The explicit collective schedule of a per-replica program, priced
    with the multi-axis ring formulas.  ``mesh``: a :class:`MeshSpec`
    (or anything its constructor takes)."""
    mesh = mesh if isinstance(mesh, MeshSpec) else MeshSpec(mesh)
    tape = build_tape(closed_jaxpr, axis_sizes=mesh.as_dict())
    events = []
    for t, op in enumerate(tape.ops):
        if op.prim not in _COLLECTIVES or not op.axes:
            continue
        if all(i in tape.literal_ids for i in op.in_ids):
            continue    # lax.psum(1, axis): axis-size arithmetic
        in_b = sum(_aval_bytes(tape.avals[i]) for i in op.in_ids)
        out_b = sum(_aval_bytes(tape.avals[i]) for i in op.out_ids)
        per_axis = ring_bytes_per_axis(
            op.prim, in_b, out_b,
            {a: mesh.size(a) for a in op.axes if a in mesh})
        per_axis = {a: b * op.scale for a, b in per_axis.items()}
        events.append(CollectiveEvent(
            t, op.prim, op.axes, in_b, per_axis, scale=op.scale))
    return ShardReport(mesh, schedule=events, unpriced=tape.unpriced)


def lint_sharded_step(closed_jaxpr, mesh, data_axes=("data",),
                      varying_invars=(), shard_dims=None,
                      param_outvars=None, param_names=None,
                      state_axes=None, disable=(), subject="<step>"):
    """Mixed-axis DST rules over a per-replica step (DST006/007/008).

    ``varying_invars``: flat invar indices whose *content* differs per
    rank along ``data_axes`` (the batch shards).  ``shard_dims``:
    ``{invar_index: {dim: (axis, ...)}}`` declaring layout-sharded
    inputs (tensor-parallel params, ZeRO optimizer-state shards).
    ``param_outvars``/``param_names``: the new-parameter outputs that
    must come back whole and replica-identical.  ``state_axes``:
    ``{invar_index: (axis, ...)}`` marking inputs (e.g. optimizer-state
    shards) that legitimately stay scattered across steps.
    """
    mesh = mesh if isinstance(mesh, MeshSpec) else MeshSpec(mesh)
    tape = build_tape(closed_jaxpr, axis_sizes=mesh.as_dict())
    data_axes = frozenset(data_axes)
    init = {}
    for idx in varying_invars:
        if 0 <= idx < len(tape.invar_ids):
            i = tape.invar_ids[idx]
            init[i] = _VState(rank=_rank_of(tape.avals[i]),
                              content=data_axes)
    for idx, dmap in (shard_dims or {}).items():
        if not (0 <= idx < len(tape.invar_ids)):
            continue
        i = tape.invar_ids[idx]
        rank = _rank_of(tape.avals[i])
        dims = [frozenset() for _ in range(rank)]
        for d, axs in dmap.items():
            if d < rank:
                dims[d] = frozenset(
                    (axs,) if isinstance(axs, str) else axs)
        st = init.get(i, _VState(rank=rank))
        init[i] = st.clone(dims=tuple(dims))

    findings = []

    def on_reduce(t, op, state, axes):
        # DST004 (tightened, docs/precision.md): a gradient reduction
        # over the data axes must run f32 on the wire — a sub-f32 float
        # accumulates one rounding per ring hop.  Scoped to the data
        # axes: a bf16 row-parallel activation psum over a model axis
        # is legitimate mixed-precision practice.
        if axes & data_axes:
            findings.extend(_reduce_dtype_findings(op, tape, subject))
        for a in sorted(axes):
            if a in state.partial:
                continue            # completes a partial sum: legit
            if a in state.layout():
                findings.append(Finding(
                    "DST006", subject,
                    "%s over axis %r reduces across LAYOUT shards: the "
                    "operand holds a different piece of the tensor on "
                    "each member of %r (a model-sharded parameter's "
                    "gradient, an optimizer shard) — summing the pieces "
                    "mixes unrelated coordinates; reduce over the data "
                    "axes only and keep per-shard math shard-local"
                    % (op.prim, a, a)))
                continue
            if a in state.content:
                continue            # the grad/batch reduction: legit
            if a in state.reduced:
                findings.append(Finding(
                    "DST008", subject,
                    "%s over axis %r overlaps a reduction already "
                    "applied on this chain (covered axes %s): psum "
                    "multiplies by the axis size per extra application "
                    "— grads come out K-scaled"
                    % (op.prim, a, sorted(state.reduced))))
                continue
            if (state.content & data_axes) and a not in data_axes:
                findings.append(Finding(
                    "DST006", subject,
                    "%s over non-data axis %r applied to a value that "
                    "varies over the data axes %s but not over %r: the "
                    "gradient reduction rides the wrong mesh axis — the "
                    "replicas never sync and the %r members get a dead "
                    "K-scaling collective"
                    % (op.prim, a, sorted(state.content & data_axes),
                       a, a)))
            else:
                findings.append(Finding(
                    "DST008", subject,
                    "%s over axis %r applied to a value with no "
                    "variance, partial sum or shard layout over it — a "
                    "dead (or duplicate) sub-axis reduction that scales "
                    "by the axis size" % (op.prim, a)))

    env = _replica_collect(tape, mesh, init, data_axes,
                           on_reduce=on_reduce)

    out_idx = (range(len(tape.outvar_ids)) if param_outvars is None
               else param_outvars)
    names = list(param_names or [])
    for j, oi in enumerate(out_idx):
        if not (0 <= oi < len(tape.outvar_ids)):
            continue
        i = tape.outvar_ids[oi]
        st = env.get(i)
        if st is None:
            continue
        name = names[j] if j < len(names) else "output %d" % oi
        if st.partial:
            findings.append(Finding(
                "DST001", name,
                "new value of %r is a PENDING PARTIAL-SUM over mesh "
                "axes %s: a completing psum was deleted (the "
                "row-parallel output reduction of a tensor-parallel "
                "layer) — every member of %s holds only its shard's "
                "addend, so the replicas train on partial activations "
                "and silently diverge"
                % (name, sorted(st.partial), sorted(st.partial))))
            continue
        if st.scattered:
            findings.append(Finding(
                "DST007", name,
                "new value of %r is still reduce-scattered over %s: the "
                "covering all_gather is missing before next-step use — "
                "every rank would apply the next step to a tensor that "
                "is mostly some OTHER rank's shard (the ZeRO-1 "
                "all-gather half of the reduce-scatter/all-gather pair)"
                % (name, sorted(st.scattered))))
            continue    # DST007 is the specific diagnosis; skip DST001
        if st.content & data_axes:
            findings.append(Finding(
                "DST001", name,
                "new value of %r still varies over mesh axes %s: its "
                "gradient is never reduced over the data axes, so "
                "replicas silently diverge after one step"
                % (name, sorted(st.content & data_axes))))
    return filter_findings(findings, disable)


def lint_ring_schedule(closed_jaxpr, axis, axis_size, disable=(),
                       subject="<ring>", outer_scale=1):
    """DST009: every scanned ``ppermute`` over ``axis`` must be a full
    single-cycle ring whose hop count equals the axis size — that is
    exactly when the modeled bytes (hops × chunk) match the ring formula
    (K × chunk) and every chunk visits every rank once.

    ``outer_scale``: how many times an ENCLOSING scan replays the whole
    ring (the pipeline schedule runs one full attention ring per tick —
    ``M + K_pipe - 1`` of them), so the expected hop count is
    ``axis_size × outer_scale``."""
    k = int(axis_size)
    outer = int(outer_scale)
    tape = build_tape(closed_jaxpr, axis_sizes={axis: k})
    findings = []
    for op in tape.ops:
        if op.prim != "ppermute" or axis not in op.axes:
            continue
        perm = tuple(tuple(p) for p in op.params.get("perm", ()))
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        chunk = sum(_aval_bytes(tape.avals[i]) for i in op.in_ids)
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            findings.append(Finding(
                "DST009", subject,
                "ppermute over %r repeats a source or destination in "
                "its perm %r: chunks are dropped or double-sent — not a "
                "ring" % (axis, perm)))
            continue
        if op.scale <= 1:
            continue    # a single halo exchange, not a scanned ring
        mapping = dict(perm)
        covered = set(srcs) == set(range(k)) == set(dsts)
        single_cycle = False
        if covered:
            seen, cur = set(), 0
            while cur not in seen:
                seen.add(cur)
                cur = mapping[cur]
            single_cycle = len(seen) == k
        if not covered or not single_cycle:
            findings.append(Finding(
                "DST009", subject,
                "scanned ppermute over %r (size %d) has perm %r which "
                "is not a single full ring over all %d members: some "
                "chunk never reaches some rank, so the attention output "
                "silently drops context" % (axis, k, perm, k)))
            continue
        if op.scale != k * outer:
            findings.append(Finding(
                "DST009", subject,
                "ring over %r scans %d hop(s) but the axis has %d "
                "members (x%d outer replays): modeled collective bytes "
                "%d do not match the ring formula %d (= K x %d-byte "
                "chunk) — the ring never completes (or over-rotates) "
                "and the modeled budget misstates the wire traffic"
                % (axis, op.scale, k, outer, op.scale * chunk,
                   k * outer * chunk, chunk)))
    return filter_findings(findings, disable)


def lint_pipeline_step(closed_jaxpr, axis_sizes, n_micro,
                       stash_bytes=None, peak_hbm_bytes=None,
                       param_outvars=(), param_names=(),
                       pipe_sharded=(), disable=(),
                       subject="<pipeline>"):
    """The two pipeline-specific bug classes (docs/pipeline.md).

    **DST011 — schedule shape / activation-stash liveness.**  The 1F1B
    step must move activations forward and cotangents backward over
    ``pipe`` as full single-cycle rings scanned exactly ``M + K - 1``
    ticks (one hop per tick; the wrap-around edge carries masked
    warm-up garbage) — any other shape means the modeled per-hop bytes
    and bubble fraction ``(K-1)/(K-1+M)`` describe a schedule the
    program does not run.  And the modeled peak HBM must hold the
    in-flight microbatch stash (``stash_bytes``, nominally M x one
    microbatch's residual activations): a tape that frees activations
    between ticks is under-modeling exactly the memory pipelining
    exists to spend.

    **DST012 — gradients reduced over ``pipe``.**  Stages hold
    DIFFERENT layers, so ``pipe`` is never a batch axis for stage-local
    parameters: any reduction over ``pipe`` (psum/pmean/pmax/
    reduce-scatter) whose result flows into a pipe-sharded parameter's
    new value mixes gradients of unrelated layers.  Found by taint
    propagation over the inlined tape: seed at every reduction over
    ``pipe``, flow forward through op outputs, flag tainted
    pipe-sharded param outvars.  (Pipe-REPLICATED params — embeddings,
    final norm, head — legitimately complete partial grads with one
    psum over ``pipe``; they are not pipe-sharded, so they never
    flag.)  Only meaningful on the per-param (non-ZeRO) spelling: the
    ZeRO-1 flat concat mixes every parameter into one vector, where
    the replicated params' legitimate psum would taint all of it."""
    k = int(axis_sizes.get("pipe", 1))
    m = int(n_micro)
    ticks = m + k - 1
    tape = build_tape(closed_jaxpr, axis_sizes=axis_sizes)
    findings = []

    pp_ops = [op for op in tape.ops
              if op.prim == "ppermute" and "pipe" in op.axes]
    if len(pp_ops) < 2:
        findings.append(Finding(
            "DST011", subject,
            "pipeline step has %d ppermute(s) over 'pipe' — the 1F1B "
            "schedule needs at least two scanned rings (activations "
            "forward, cotangents backward); the stage boundaries are "
            "not being crossed the modeled way" % len(pp_ops)))
    for op in pp_ops:
        perm = tuple(tuple(p) for p in op.params.get("perm", ()))
        mapping = dict(perm)
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        covered = (len(set(srcs)) == len(srcs)
                   and len(set(dsts)) == len(dsts)
                   and set(srcs) == set(range(k)) == set(dsts))
        single_cycle = False
        if covered:
            seen, cur = set(), 0
            while cur not in seen:
                seen.add(cur)
                cur = mapping.get(cur, cur)
            single_cycle = len(seen) == k
        if not covered or not single_cycle:
            findings.append(Finding(
                "DST011", subject,
                "pipeline ppermute over 'pipe' (size %d) has perm %r "
                "which is not one full single-cycle ring: some stage's "
                "activation never reaches its successor" % (k, perm)))
            continue
        if op.scale != ticks:
            findings.append(Finding(
                "DST011", subject,
                "pipeline ppermute over 'pipe' scans %d tick(s) but "
                "the 1F1B schedule of %d microbatches over %d stages "
                "runs %d (= M + K - 1): the modeled per-hop bytes and "
                "the bubble fraction (K-1)/(K-1+M) describe a "
                "different schedule" % (op.scale, m, k, ticks)))

    if stash_bytes and peak_hbm_bytes is not None \
            and int(peak_hbm_bytes) < int(stash_bytes):
        findings.append(Finding(
            "DST011", subject,
            "modeled peak HBM %d bytes is below the in-flight "
            "activation stash %d bytes (%d microbatches x one "
            "microbatch's residual activations): the memory story "
            "does not reflect the microbatches the schedule keeps "
            "live for the backward pass"
            % (int(peak_hbm_bytes), int(stash_bytes), m)))

    if param_outvars:
        reducers = ("psum", "pmean", "pmax", "pmin", "psum_scatter",
                    "reduce_scatter", "all_to_all")
        tainted = set()
        for op in tape.ops:
            seeded = (op.prim in reducers and "pipe" in op.axes)
            if seeded or any(i in tainted for i in op.in_ids):
                tainted.update(op.out_ids)
        pipe_sharded = set(pipe_sharded)
        names = list(param_names) or [
            "param[%d]" % i for i in range(len(param_outvars))]
        for pi, ov in enumerate(param_outvars):
            if pi not in pipe_sharded:
                continue
            if 0 <= ov < len(tape.outvar_ids) \
                    and tape.outvar_ids[ov] in tainted:
                findings.append(Finding(
                    "DST012", names[pi],
                    "new value of %r (stage-local, sharded over "
                    "'pipe') is downstream of a reduction over the "
                    "'pipe' axis: stages hold DIFFERENT layers, so "
                    "this update mixes gradients of unrelated "
                    "parameters across stages — reduce stage-local "
                    "gradients over the batch axes only"
                    % (names[pi],)))
    return filter_findings(findings, disable)


# ---------------------------------------------------------------------------
# global view: GSPMD-style spec propagation with inferred collectives
# ---------------------------------------------------------------------------
def _reshard_cost(mesh, aval, src, dst):
    """(kind, axes, wire bytes) to move one value from spec ``src`` to
    ``dst``: moved axes ride an all_to_all of the local tile, removed
    axes an all_gather; newly-added sharding is a free local slice."""
    src_axes, dst_axes = src.axes(), dst.axes()
    removed = sorted(src_axes - dst_axes)
    moved = sorted(a for a in (src_axes & dst_axes)
                   if [a in d for d in src.dims]
                   != [a in d for d in dst.dims])
    local = src.local_bytes(aval, mesh)
    cost = 0
    for a in moved:
        ka = mesh.size(a)
        cost += (ka - 1) * local // max(ka, 1)
    for a in removed:
        ka = mesh.size(a)
        cost += (ka - 1) * local    # all_gather: (K-1)/K x (K x local)
        local *= ka
    kind = "all_to_all" if moved else "all_gather"
    return kind, tuple(moved + removed), cost


def _reshape_dim_map(src_shape, dst_shape):
    """{src_dim: dst_dim} for dims preserved 1:1 by a row-major reshape
    (cumulative-product alignment); split/merged dims are unmapped."""
    out = {}
    si = di = 0
    while si < len(src_shape) and di < len(dst_shape):
        s, d = int(src_shape[si]), int(dst_shape[di])
        if s == d:
            out[si] = di
            si += 1
            di += 1
            continue
        # a split/merge region: accumulate the smaller side until the
        # running products align; nothing inside the region maps 1:1
        sp, dp = s, d
        s2, d2 = si + 1, di + 1
        while sp != dp:
            if sp < dp and s2 < len(src_shape):
                sp *= int(src_shape[s2])
                s2 += 1
            elif d2 < len(dst_shape):
                dp *= int(dst_shape[d2])
                d2 += 1
            else:
                return out
        si, di = s2, d2
    return out


def propagate(closed_jaxpr, mesh, in_specs, donated_invars=(),
              subject="<program>"):
    """GSPMD-style whole-program sharding propagation (global view).

    ``in_specs``: one ``PartitionSpec``/``ShardSpec``/None per flat
    invar.  Returns a :class:`ShardReport` whose schedule holds the
    *inferred* collectives (partial-sum psums from contracted sharded
    dims, reduced sharded dims) and whose ``reshards`` list every forced
    layout change (hidden-collective class, DST010).
    """
    mesh = mesh if isinstance(mesh, MeshSpec) else MeshSpec(mesh)
    tape = build_tape(closed_jaxpr)
    env = {}
    for idx, i in enumerate(tape.invar_ids):
        spec = in_specs[idx] if idx < len(in_specs) else None
        env[i] = ShardSpec.from_partition_spec(
            spec, _rank_of(tape.avals[i]))

    schedule, reshards = [], []

    def spec_of(i):
        s = env.get(i)
        if s is None:
            s = ShardSpec.replicated(_rank_of(tape.avals.get(i)))
            env[i] = s
        return s

    def infer_psum(t, spec, aval, note):
        """Flush a pending partial-sum: the all-reduce GSPMD inserts."""
        if not spec.partial:
            return spec
        local = spec.local_bytes(aval, mesh)
        per_axis = ring_bytes_per_axis(
            "psum", local, local,
            {a: mesh.size(a) for a in sorted(spec.partial)})
        schedule.append(CollectiveEvent(
            t, "psum", sorted(spec.partial), local, per_axis,
            inferred=True, note=note))
        return ShardSpec(spec.dims)

    def force(t, op, i, want):
        """Reshard operand ``i`` to ``want``, recording the event when
        wire traffic is forced (gaining sharding is a free local
        slice).  The env is updated: after the reshard the value exists
        in the new layout, so later uses do not pay again."""
        have = spec_of(i)
        want = ShardSpec(want.dims, have.partial)
        if have.dims == want.dims:
            return
        kind, axes, cost = _reshard_cost(mesh, tape.avals[i], have, want)
        env[i] = want
        if not axes:
            return
        reshards.append(ReshardEvent(
            t, op.prim, kind, axes, cost * op.scale,
            note="operand sharding %r forced to %r"
                 % (have.dims, want.dims)))

    for t, op in enumerate(tape.ops):
        in_specs_op = [spec_of(i) for i in op.in_ids]
        out_avals = [tape.avals[i] for i in op.out_ids]
        out_rank = _rank_of(out_avals[0]) if out_avals else 0

        # any operand still carrying a partial sum gets its inferred
        # psum flushed before use (GSPMD sinks further; pricing at first
        # use is the deterministic upper bound) — except a reducing
        # collective over exactly those axes, which completes it for free
        for k_i, i in enumerate(op.in_ids):
            s = in_specs_op[k_i]
            if s.partial and not (
                    op.prim in _REDUCING
                    and s.partial <= frozenset(op.axes)):
                env[i] = infer_psum(t, s, tape.avals[i],
                                    "partial sum consumed by %s" % op.prim)
                in_specs_op[k_i] = env[i]

        if op.prim == "dot_general":
            lhs, rhs = in_specs_op[0], in_specs_op[1]
            (lc, rc), (lb, rb) = op.params["dimension_numbers"]
            contracted = set()
            rhs_dims = list(rhs.dims)
            mismatch = False
            for dl, dr in zip(lc, rc):
                la = set(lhs.dims[dl]) if dl < len(lhs.dims) else set()
                ra = set(rhs_dims[dr]) if dr < len(rhs_dims) else set()
                if la == ra:
                    contracted |= la
                else:
                    # both sides of a contraction must agree on the
                    # contracted dim's layout: align rhs onto lhs
                    rhs_dims[dr] = tuple(sorted(la))
                    mismatch = True
                    contracted |= la
            if mismatch:
                force(t, op, op.in_ids[1], ShardSpec(rhs_dims))
                rhs = spec_of(op.in_ids[1])
            lfree = [d for d in range(len(lhs.dims))
                     if d not in set(lc) | set(lb)]
            rfree = [d for d in range(len(rhs.dims))
                     if d not in set(rc) | set(rb)]
            dims = [lhs.dims[d] for d in lb] \
                + [lhs.dims[d] for d in lfree] \
                + [rhs.dims[d] for d in rfree]
            dims = (dims + [()] * out_rank)[:out_rank]
            new = ShardSpec(dims,
                            lhs.partial | rhs.partial | contracted)
        elif op.prim.startswith("reduce_") and "axes" in op.params \
                and op.prim not in _COLLECTIVES:
            src = in_specs_op[0] if in_specs_op else \
                ShardSpec.replicated(0)
            red = set(op.params["axes"])
            partial = set(src.partial)
            dims = []
            for d, axs in enumerate(src.dims):
                if d in red:
                    partial |= set(axs)   # reducing a sharded dim:
                else:                     # each shard holds an addend
                    dims.append(axs)
            new = ShardSpec((tuple(dims) + ((),) * out_rank)[:out_rank],
                            partial)
        elif op.prim == "transpose":
            src = in_specs_op[0]
            perm = op.params["permutation"]
            new = ShardSpec(tuple(src.dims[p] if p < len(src.dims)
                                  else () for p in perm), src.partial)
        elif op.prim == "broadcast_in_dim":
            src = in_specs_op[0] if in_specs_op else None
            bdims = op.params.get("broadcast_dimensions", ())
            dims = [()] * out_rank
            if src is not None:
                for sd, od in enumerate(bdims):
                    if sd < len(src.dims) and od < out_rank:
                        dims[od] = src.dims[sd]
            new = ShardSpec(dims, src.partial if src else ())
        elif op.prim == "reshape":
            src = in_specs_op[0]
            src_shape = getattr(tape.avals[op.in_ids[0]], "shape", ())
            dst_shape = getattr(out_avals[0], "shape", ())
            dmap = _reshape_dim_map(src_shape, dst_shape)
            dims = [()] * out_rank
            for sd, od in dmap.items():
                if sd < len(src.dims):
                    dims[od] = src.dims[sd]
            lost = src.axes() - frozenset(a for d in dims for a in d)
            if lost:
                # a sharded dim was split/merged: GSPMD reshards
                force(t, op, op.in_ids[0], ShardSpec.replicated(
                    len(src.dims)))
            new = ShardSpec(dims, src.partial)
        elif op.prim in ("convert_element_type", "copy", "stop_gradient",
                         "device_put", "sharding_constraint"):
            new = in_specs_op[0] if in_specs_op else \
                ShardSpec.replicated(out_rank)
            if op.prim == "sharding_constraint":
                want = op.params.get("sharding")
                spec = getattr(want, "spec", None)
                if spec is not None:
                    target = ShardSpec.from_partition_spec(spec, out_rank)
                    force(t, op, op.in_ids[0], target)
                    new = ShardSpec(target.dims, new.partial)
        else:
            # default: elementwise/unhandled.  Same-rank operands with
            # agreeing dims keep them; a dim where two sharded operands
            # disagree forces the minority onto the first operand's
            # layout (recorded); rank changes degrade to replicated.
            cands = [s for s in in_specs_op if len(s.dims) == out_rank]
            dims = [()] * out_rank
            partial = frozenset().union(*(s.partial
                                          for s in in_specs_op)) \
                if in_specs_op else frozenset()
            if cands:
                # the most-sharded operand wins (replicated operands
                # slice down for free); disagreeing sharded operands
                # are forced onto it — the DST010 hidden-collective
                base = max(cands, key=lambda s: s.shard_factor(mesh))
                dims = list(base.dims)
                for k_i, i in enumerate(op.in_ids):
                    s = in_specs_op[k_i]
                    if len(s.dims) != out_rank:
                        continue
                    if s.dims != base.dims and s.axes():
                        force(t, op, i, base)
            new = ShardSpec(dims, partial)

        for o in op.out_ids:
            env[o] = new

    out_specs = []
    for t_out, i in enumerate(tape.outvar_ids):
        s = spec_of(i)
        if s.partial:
            s = infer_psum(len(tape.ops), s, tape.avals[i],
                           "partial sum at program output")
            env[i] = s
        out_specs.append(s)
    return ShardReport(mesh,
                       in_specs=[spec_of(i) for i in tape.invar_ids],
                       out_specs=out_specs, schedule=schedule,
                       reshards=reshards, unpriced=tape.unpriced)


def lint_global_sharding(closed_jaxpr, mesh, in_specs, disable=(),
                         subject="<program>"):
    """DST010 (+ COST004) over a global-view program: every forced
    reshard of an intermediate is a hidden collective GSPMD would
    silently insert inside the step body."""
    report = propagate(closed_jaxpr, mesh, in_specs, subject=subject)
    findings = []
    for ev in report.reshards:
        findings.append(Finding(
            "DST010", subject,
            "activation resharding forced inside the step body at eqn "
            "%d (%s): operand layouts disagree, so GSPMD inserts a "
            "hidden %s over %s moving %d modeled bytes every step — "
            "annotate the producer/consumer to agree, or make the "
            "collective explicit so it is budgeted"
            % (ev.index, ev.prim, ev.kind, "x".join(ev.axes) or "?",
               ev.wire_bytes)))
    findings += unpriced_findings(report, subject=subject)
    return filter_findings(findings, disable), report


def shard_summary(reports, findings=()):
    """Machine-readable ``shard`` section for the CLI ``--json``
    output (schema_version 3): {model: ShardReport.as_dict()} plus the
    shard-rule findings."""
    return {
        "rules": ["DST006", "DST007", "DST008", "DST009", "DST010",
                  "DST011", "DST012", "COST004"],
        "reports": {name: (rep.as_dict() if hasattr(rep, "as_dict")
                           else rep)
                    for name, rep in sorted((reports or {}).items())},
        "findings": [f.as_dict() for f in findings
                     if f.rule_id.startswith(("DST", "COST"))],
    }
