"""SRV005: promotion decisions come from registry metrics, never clocks.

The promotion controller's whole value is that its decision sequence is
*replayable*: the headline mlops test reruns a full train→canary→
rollback cycle and byte-compares the audit trail.  One ``time.time()``
in the decision path quietly breaks that — a ramp that advances "after
30 seconds" instead of "after N canary requests" makes every rerun a
different experiment, and an audit record stamped with wall-clock
evidence can never be diffed.  The deterministic spine is: evidence =
PR-9 registry metrics, ramp = pinned fraction schedule, traffic split =
seeded hash.  This sweep keeps it that way structurally:

- every ``mxnet_tpu/mlops/*.py`` file plus the decision CLIs
  (``tools/promote.py``, ``tools/capacity.py``) is AST-scanned for
  wall-clock reads: ``time.time/monotonic/perf_counter/process_time/
  thread_time/monotonic_ns/time_ns/perf_counter_ns``, ``time.sleep``
  (a sleep in a decision loop is a schedule-by-clock in disguise) and
  ``datetime.now/utcnow/today/datetime.datetime.now``;
- a finding is an ERROR; a *measurement* of the system under test (the
  mlops bench timing the controller, a CLI's progress display) carries
  an inline ``# mxlint: disable=SRV005`` with its justification — the
  same escape hatch the SRC004 example sweeps use, visible in review.

Wired into ``--self-check`` via ``lint_promotion_sources`` (the DOC001
discipline: the rule row lives in docs/analysis.md).
"""
from __future__ import annotations

import ast
import glob
import os

from .findings import Finding, filter_findings

__all__ = ["lint_wallclock_reads", "lint_promotion_sources",
           "lint_supervisor_sources", "WALLCLOCK_ATTRS"]

# attribute names that read (or schedule by) the wall clock when called
# on a time/datetime module or datetime class
WALLCLOCK_ATTRS = frozenset({
    "time", "monotonic", "perf_counter", "process_time", "thread_time",
    "monotonic_ns", "time_ns", "perf_counter_ns", "sleep",
    "now", "utcnow", "today",
})

# receivers the attribute must hang off for a confident match: bare
# ``obj.now()`` on an arbitrary object is not a clock read, but
# ``time.``/``datetime.``/``date.`` prefixed calls are
_CLOCK_ROOTS = frozenset({"time", "datetime", "date"})


def _line_suppressions(source):
    """{lineno: rule ids} from trailing ``# mxlint: disable=...``."""
    from .findings import _DISABLE_RE
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _DISABLE_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def _clock_root(node):
    """The dotted root name of an attribute chain (``time`` in
    ``time.perf_counter``, ``datetime`` in ``datetime.datetime.now``),
    or None for computed receivers."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def lint_wallclock_reads(path=None, source=None):
    """Scan one source file for wall-clock reads (see module docstring).
    Pure AST; honors inline ``# mxlint: disable=SRV005`` per line."""
    if source is None:
        with open(path) as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path or "<string>")
    except SyntaxError as e:
        return [Finding("SRV005", path or "<string>",
                        "source does not parse: %s" % e)]
    suppressed = _line_suppressions(source)
    subject = path or "<string>"
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr not in WALLCLOCK_ATTRS:
            continue
        root = _clock_root(node.func.value)
        if root not in _CLOCK_ROOTS:
            continue
        if "SRV005" in suppressed.get(node.lineno, ()):
            continue
        out.append(Finding(
            "SRV005", "%s:%d" % (subject, node.lineno),
            "wall-clock call %s.%s() in the promotion/capacity decision "
            "path — decisions must be driven by registry metrics and "
            "pinned schedules so reruns replay byte-identically; if "
            "this line only *measures* the system under test, mark it "
            "with an inline `# mxlint: disable=SRV005` and say why"
            % (root, attr)))
    return out


def lint_promotion_sources(disable=(), root=None):
    """The SRV005 sweep ``--self-check`` runs: ``mxnet_tpu/mlops/*.py``
    plus the decision CLIs (``tools/promote.py``, ``tools/capacity.py``).
    Skipped silently outside a repo checkout (tools absent)."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    root = root or os.path.dirname(pkg)           # mxnet_tpu/
    repo = os.path.dirname(root)
    targets = sorted(glob.glob(os.path.join(root, "mlops", "*.py")))
    for name in ("promote.py", "capacity.py"):
        path = os.path.join(repo, "tools", name)
        if os.path.isfile(path):
            targets.append(path)
    findings = []
    for path in targets:
        try:
            findings += lint_wallclock_reads(os.path.normpath(path))
        except OSError:
            continue
    return filter_findings(findings, disable)


def lint_supervisor_sources(disable=(), root=None):
    """The SRV005 sweep over the elastic supervisor's decision path
    (``resilience/supervisor.py`` plus the ``tools/train_elastic.py``
    driver): shrink/grow/steps-lost decisions must be pure functions of
    heartbeat counters, manifest steps and exit codes so the audit
    trail replays byte-identically — the same no-wall-clock contract
    the promotion controller carries.  The watch loop's child-process
    poll pacing is measurement and carries the inline justified
    ``# mxlint: disable=SRV005`` escape.  Wired into ``--self-check``."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    root = root or os.path.dirname(pkg)           # mxnet_tpu/
    repo = os.path.dirname(root)
    targets = [os.path.join(root, "resilience", "supervisor.py")]
    driver = os.path.join(repo, "tools", "train_elastic.py")
    if os.path.isfile(driver):
        targets.append(driver)
    findings = []
    for path in targets:
        try:
            findings += lint_wallclock_reads(os.path.normpath(path))
        except OSError:
            continue
    return filter_findings(findings, disable)
