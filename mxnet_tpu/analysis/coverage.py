"""Test-coverage map: which test exercises each registered op.

The sweep cases live in ``tests/test_op_sweep.py`` (``CASES`` +
``ALSO_COVERED``); this module loads them without pytest, builds the
{op_name: coverage description} map REG010 lints against, and generates
``tests/OP_COVERAGE.md`` (``python -m mxnet_tpu.analysis --coverage``) —
the table is a build artifact of the registry + test map, never
hand-maintained.
"""
from __future__ import annotations

import importlib.util
import os
import sys

from ..ops import registry as _reg
from .registry_lint import unique_ops

__all__ = ["find_tests_dir", "load_test_map", "generate_coverage_md",
           "build_rows"]

_TEST_MOD_NAME = "_mxlint_op_sweep_map"


def find_tests_dir(start=None):
    """Locate the repo's tests/ directory by walking up from this package
    (site-installs without the test tree return None; REG010 then skips)."""
    here = start or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for _ in range(4):
        cand = os.path.join(here, "tests")
        if os.path.isfile(os.path.join(cand, "test_op_sweep.py")):
            return cand
        here = os.path.dirname(here)
    return None


def _load_sweep_module(tests_dir):
    if _TEST_MOD_NAME in sys.modules:
        return sys.modules[_TEST_MOD_NAME]
    path = os.path.join(tests_dir, "test_op_sweep.py")
    spec = importlib.util.spec_from_file_location(_TEST_MOD_NAME, path)
    mod = importlib.util.module_from_spec(spec)
    # registered under a private name so pytest's own import of
    # tests.test_op_sweep is not clobbered; tests_dir goes on sys.path for
    # the sweep's sibling imports (op_sweep_deep_cases)
    sys.modules[_TEST_MOD_NAME] = mod
    # the sweep's sibling (op_sweep_deep_cases) does `from test_op_sweep
    # import ...`; alias the real name too so that import resolves to this
    # very module instead of restarting the import cycle
    alias_real = "test_op_sweep" not in sys.modules
    if alias_real:
        sys.modules["test_op_sweep"] = mod
    sys.path.insert(0, tests_dir)
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(_TEST_MOD_NAME, None)
        if alias_real:
            sys.modules.pop("test_op_sweep", None)
        raise
    finally:
        sys.path.remove(tests_dir)
    return mod


def load_test_map(tests_dir=None):
    """{op_name: coverage description} or None when tests aren't present."""
    tests_dir = tests_dir or find_tests_dir()
    if tests_dir is None:
        return None
    try:
        mod = _load_sweep_module(tests_dir)
    except Exception:
        return None
    return build_map(mod.CASES, mod.ALSO_COVERED)


def build_map(cases, also_covered):
    out = {}
    for name, case_list in cases.items():
        out[name] = "sweep (%d cases)" % len(case_list)
    for name, where in also_covered.items():
        out.setdefault(name, where)
    return out


def lookup(coverage_map, op, registry=None):
    """Coverage entry for ``op``, matching any of its registered aliases
    (sweep cases are keyed by whichever name the sweep exercises)."""
    registry = registry or _reg
    if op.name in coverage_map:
        return coverage_map[op.name]
    for name in registry.list_ops():
        if registry.get(name) is op and name in coverage_map:
            where = coverage_map[name]
            return "%s (as %s)" % (where, name) if "(as " not in where \
                else where
    return None


def build_rows(cases, also_covered, registry=None):
    """[(op, coverage)] over unique ops + the uncovered subset."""
    registry = registry or _reg
    cov = build_map(cases, also_covered)
    rows, uncovered = [], []
    for name, op in sorted(unique_ops(registry).items()):
        where = lookup(cov, op, registry)
        if where is None:
            rows.append((name, "NOT COVERED"))
            uncovered.append(name)
        else:
            rows.append((name, where))
    return rows, uncovered


def generate_coverage_md(path=None, cases=None, also_covered=None,
                         registry=None):
    """Write tests/OP_COVERAGE.md; returns (rows, uncovered).

    ``cases``/``also_covered`` default to the live test map (loaded from
    tests/test_op_sweep.py); the coverage test passes its own so the file
    it asserts on is built from the module pytest actually collected.
    """
    registry = registry or _reg
    if cases is None or also_covered is None:
        tests_dir = find_tests_dir()
        if tests_dir is None:
            raise RuntimeError("tests/test_op_sweep.py not found; cannot "
                               "build the coverage map")
        mod = _load_sweep_module(tests_dir)
        cases = cases if cases is not None else mod.CASES
        also_covered = also_covered if also_covered is not None \
            else mod.ALSO_COVERED
    rows, uncovered = build_rows(cases, also_covered, registry)
    if path is None:
        path = os.path.join(find_tests_dir(), "OP_COVERAGE.md")
    n_sweep = len([r for r in rows if r[1].startswith("sweep")])
    n_dedicated = len(rows) - n_sweep - len(uncovered)
    with open(path, "w") as f:
        f.write("# Operator test coverage\n\n")
        f.write("Generated by `python -m mxnet_tpu.analysis --coverage` "
                "— do not edit by hand.\n\n")
        f.write("%d unique ops (%d registered names); %d swept, %d covered "
                "by dedicated files, %d uncovered.\n\n"
                % (len(rows), len(registry.list_ops()), n_sweep,
                   n_dedicated, len(uncovered)))
        f.write("| op | covered by |\n|---|---|\n")
        for name, where in rows:
            f.write("| %s | %s |\n" % (name, where))
    return rows, uncovered
