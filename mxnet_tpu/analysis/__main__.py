"""CLI: ``python -m mxnet_tpu.analysis [target] [options]``.

Targets:
  ``--self-check``        registry lint over the live registry (CI tier-1)
                          + docs sync + cost-pass determinism
  ``--coverage``          regenerate tests/OP_COVERAGE.md from the registry
                          + test map; fails if any op has zero coverage
  ``--cost``              static cost/memory analysis (hardware-free):
                          over a symbol target, over ``--model`` budget
                          models, or — with ``--budget FILE`` — the
                          STATIC_BUDGETS.json CI gate (COST001/COST002)
                          including each trainer model's DST lint
  ``--race``              mxrace concurrency lint (docs/concurrency.md):
                          over a ``script.py`` target, or — bare — the
                          whole-repo sweep of the threaded host tiers
                          plus the lock-order/hierarchy sync; adds the
                          ``race`` section to ``--json`` (schema 5)
  ``script.py``           AST source lint for trace-time traps
  ``symbol.json``         graph lint a saved Symbol (``Symbol.save``)

Options:
  ``--json``              machine-readable output (schema in docs/analysis.md;
                          ``schema_version`` 2 added cost/dist sections,
                          3 adds the ``--shard`` shard section)
  ``--shard``             with --cost: mxshard sharding propagation —
                          collective schedules (explicit + inferred),
                          forced reshards, the ZeRO-1 memory proof
  ``--strict``            exit 1 on warnings (default for --self-check)
  ``--disable R1,R2``     mute rules globally
  ``--shapes "data=(1,3,224,224),label=(1,)"``
                          argument shapes for the graph pass (enables the
                          large-constant trace check) and the cost pass
  ``--serving``           with a symbol target: also run the SRV rules
                          (recompile-free bucket serving; --shapes feeds
                          the batch-polymorphism probe)
  ``--hbm-cap BYTES``     with --serving: SRV003 cap on per-bucket
                          modeled peak HBM
  ``--model M1,M2``       with --cost: budget models to analyze
                          (default: every non-heavy registered model)
  ``--codegen``           with --cost: print the mxgen lowered plan per
                          shipped fusion chain (generated kernel name,
                          byte contract, emitted Pallas body); adds the
                          ``codegen`` section to ``--json`` (schema 6)
  ``--budget FILE``       with --cost: gate modeled metrics against the
                          checked-in budgets (exit 2 on COST001/DST001)
"""
from __future__ import annotations

import argparse
import ast
import sys


def _parse_shapes(text):
    if not text:
        return None
    out = {}
    # "name=(1,2),other=(3,)" — split on commas not inside parens
    depth, start, parts = 0, 0, []
    for i, ch in enumerate(text):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(text[start:i])
            start = i + 1
    parts.append(text[start:])
    for part in parts:
        if not part.strip():
            continue
        name, _, val = part.partition("=")
        out[name.strip()] = tuple(ast.literal_eval(val.strip()))
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="mxlint: static graph/registry linter for mxnet_tpu")
    p.add_argument("target", nargs="?",
                   help="a .py script (source lint) or .json symbol "
                        "(graph lint)")
    p.add_argument("--self-check", action="store_true",
                   help="registry lint over the live registry")
    p.add_argument("--coverage", action="store_true",
                   help="regenerate tests/OP_COVERAGE.md and fail on "
                        "uncovered ops")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on warnings too")
    p.add_argument("--disable", default="",
                   help="comma-separated rule ids to mute")
    p.add_argument("--shapes", default="",
                   help="arg shapes for graph lint, e.g. "
                        "\"data=(1,3,224,224)\"")
    p.add_argument("--no-consts", action="store_true",
                   help="skip the trace-based large-constant check")
    p.add_argument("--serving", action="store_true",
                   help="with a .json symbol target: also run the SRV "
                        "serving rules (recompile-free bucket execution; "
                        "needs --shapes for the batch-polymorphism probe)")
    p.add_argument("--cost", action="store_true",
                   help="static cost/memory analysis: of the symbol "
                        "target, of --model budget models, or the "
                        "--budget gate")
    p.add_argument("--budget", default="",
                   help="with --cost: STATIC_BUDGETS.json path to gate "
                        "modeled metrics against (COST001 on regression)")
    p.add_argument("--model", default="",
                   help="with --cost: comma-separated budget-model names "
                        "(see analysis/budget_models.py)")
    p.add_argument("--shard", action="store_true",
                   help="with --cost: run the mxshard sharding-"
                        "propagation pass — collective schedules, "
                        "reshards and the ZeRO-1 memory proof for the "
                        "shard-aware budget models; adds the 'shard' "
                        "section to --json (schema_version 3)")
    p.add_argument("--fusion", action="store_true",
                   help="with --cost: run the mxfuse fusion-candidate "
                        "pass — fusable chains ranked by modeled "
                        "bytes-saved-if-fused over the budget models' "
                        "unfused spellings (docs/fusion.md); adds the "
                        "'fusion' section to --json (schema_version 4)")
    p.add_argument("--codegen", action="store_true",
                   help="with --cost: print the mxgen lowered plan per "
                        "shipped fusion chain — generated kernel name, "
                        "provable-lowering status, byte contract and the "
                        "emitted Pallas body (docs/fusion.md \"Generated "
                        "kernels\"); adds the 'codegen' section to "
                        "--json (schema_version 6)")
    p.add_argument("--race", action="store_true",
                   help="mxrace concurrency lint: of a .py target, or "
                        "(bare) the whole-repo sweep over the threaded "
                        "host tiers — lock-guard inference, lock-order/"
                        "hierarchy sync, blocking-under-lock, thread "
                        "lifecycle, callback discipline "
                        "(docs/concurrency.md); adds the 'race' section "
                        "to --json (schema_version 5)")
    p.add_argument("--hbm-cap", type=int, default=0, dest="hbm_cap",
                   help="with --serving: flag buckets whose modeled peak "
                        "HBM exceeds this many bytes (SRV003)")
    args = p.parse_args(argv)

    from . import (self_check, lint_file, lint_symbol, lint_serving,
                   generate_coverage_md, render_text, render_json,
                   exit_code)
    disable = tuple(r.strip() for r in args.disable.split(",") if r.strip())

    if args.coverage:
        rows, uncovered = generate_coverage_md()
        n = len(rows)
        print("OP_COVERAGE.md: %d ops, %d uncovered" % (n, len(uncovered)))
        for name in uncovered:
            print("  NOT COVERED: %s" % name)
        return 1 if uncovered else 0

    if args.self_check:
        findings = self_check(disable=disable)
        print(render_json(findings) if args.as_json
              else render_text(findings, title="mxlint --self-check"))
        # the shipped registry must be clean: warnings fail too
        return exit_code(findings, strict=True)

    if args.race:
        from .race_lint import (lint_race_file, lint_threaded_sources,
                                race_summary)
        if args.target:
            findings = lint_race_file(args.target, disable=disable)
            title = "mxrace %s" % args.target
            print(render_json(findings) if args.as_json
                  else render_text(findings, title=title))
            return exit_code(findings, strict=args.strict)
        findings = lint_threaded_sources(disable=disable)
        if args.as_json:
            print(render_json(findings, race=race_summary()))
        else:
            print(render_text(findings, title="mxrace sweep"))
            summary = race_summary()
            print("mxrace: %d files, %d locks, %d guarded attrs, "
                  "%d lock-order edges (%d pinned)"
                  % (summary["n_files"], len(summary["locks"]),
                     len(summary["guards"]), len(summary["edges"]),
                     len(summary["hierarchy"])))
        return exit_code(findings, strict=args.strict)

    if args.cost and not (args.target and args.target.endswith(".json")):
        return _run_cost(args, disable)

    if not args.target:
        p.error("give a target script/symbol, --self-check, --coverage, "
                "or --cost")

    if args.target.endswith(".json"):
        from ..symbol import load
        sym = load(args.target)
        shapes = _parse_shapes(args.shapes)
        findings = lint_symbol(sym, shapes=shapes, disable=disable,
                               check_consts=not args.no_consts)
        if args.serving:
            findings += lint_serving(sym, data_shapes=shapes,
                                     disable=disable,
                                     hbm_cap_bytes=args.hbm_cap or None)
        cost = None
        if args.cost:
            from .cost import analyze_symbol
            report = analyze_symbol(sym, shapes=shapes)
            if report is not None:
                cost = {args.target: report}
        title = "mxlint graph %s" % args.target
        if args.as_json:
            print(render_json(findings, cost=cost))
        else:
            print(render_text(findings, title=title))
            if cost:
                for name, rep in sorted(cost.items()):
                    print(rep.render(title="mxcost %s" % name))
        return exit_code(findings, strict=args.strict)

    findings = lint_file(args.target, disable=disable)
    title = "mxlint source %s" % args.target
    print(render_json(findings) if args.as_json
          else render_text(findings, title=title))
    return exit_code(findings, strict=args.strict)


def _run_cost(args, disable):
    """--cost over budget models / the --budget CI gate."""
    import os

    # hardware-free by contract: when the caller did not pick a backend,
    # pin to CPU so a hung TPU init can never starve the static pass
    # (the BENCH_r05 motivation).  Explicit JAX_PLATFORMS wins.
    if not os.environ.get("JAX_PLATFORMS"):
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from . import render_json, render_text, exit_code, filter_findings
    from .budget_models import (BUDGET_MODELS, build_model,
                                build_fusion_report, check_budgets)
    from .dist_lint import dist_summary
    from .shard_prop import shard_summary

    cost, shards, findings = {}, {}, []
    if args.budget:
        findings, reports, shards = check_budgets(args.budget)
        findings = filter_findings(findings, disable)
        cost = reports
        title = "mxcost --budget %s" % args.budget
    else:
        names = [m.strip() for m in args.model.split(",") if m.strip()] \
            or [m for m in sorted(BUDGET_MODELS)
                if m != "resnet50_train_step"]
        for name in names:
            report, dst, shard = build_model(name)
            cost[name] = report
            if shard is not None:
                shards[name] = shard
            findings += filter_findings(dst, disable)
        title = "mxcost %s" % ",".join(names)
    fusion = {}
    if args.fusion:
        for name in sorted(cost):
            frep = build_fusion_report(name)
            if frep is not None:
                fusion[name] = frep
    codegen = None
    if args.codegen:
        from .codegen import codegen_plans
        codegen = codegen_plans()
    axis_sizes = {}
    for rep in cost.values():
        axis_sizes.update(rep.axis_sizes)
    if args.as_json:
        print(render_json(
            findings, cost=cost,
            dist=dist_summary(findings, axis_sizes=axis_sizes),
            shard=shard_summary(shards, findings)
            if (args.shard and shards) else None,
            fusion=fusion if (args.fusion and fusion) else None,
            codegen=codegen))
    else:
        print(render_text(findings, title=title))
        for name, rep in sorted(cost.items()):
            print(rep.render(title="mxcost %s" % name))
        if args.shard:
            for name, rep in sorted(shards.items()):
                print(rep.render(title="mxshard %s" % name))
        if args.fusion:
            for name, rep in sorted(fusion.items()):
                print(rep.render(title="mxfuse %s" % name))
        if codegen is not None:
            from .codegen import render_codegen
            print(render_codegen(codegen))
    return exit_code(findings, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
