"""CLI: ``python -m mxnet_tpu.analysis [target] [options]``.

Targets:
  ``--self-check``        registry lint over the live registry (CI tier-1)
  ``--coverage``          regenerate tests/OP_COVERAGE.md from the registry
                          + test map; fails if any op has zero coverage
  ``script.py``           AST source lint for trace-time traps
  ``symbol.json``         graph lint a saved Symbol (``Symbol.save``)

Options:
  ``--json``              machine-readable output (schema in docs/analysis.md)
  ``--strict``            exit 1 on warnings (default for --self-check)
  ``--disable R1,R2``     mute rules globally
  ``--shapes "data=(1,3,224,224),label=(1,)"``
                          argument shapes for the graph pass (enables the
                          large-constant trace check)
  ``--serving``           with a symbol target: also run the SRV rules
                          (recompile-free bucket serving; --shapes feeds
                          the batch-polymorphism probe)
"""
from __future__ import annotations

import argparse
import ast
import sys


def _parse_shapes(text):
    if not text:
        return None
    out = {}
    # "name=(1,2),other=(3,)" — split on commas not inside parens
    depth, start, parts = 0, 0, []
    for i, ch in enumerate(text):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(text[start:i])
            start = i + 1
    parts.append(text[start:])
    for part in parts:
        if not part.strip():
            continue
        name, _, val = part.partition("=")
        out[name.strip()] = tuple(ast.literal_eval(val.strip()))
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="mxlint: static graph/registry linter for mxnet_tpu")
    p.add_argument("target", nargs="?",
                   help="a .py script (source lint) or .json symbol "
                        "(graph lint)")
    p.add_argument("--self-check", action="store_true",
                   help="registry lint over the live registry")
    p.add_argument("--coverage", action="store_true",
                   help="regenerate tests/OP_COVERAGE.md and fail on "
                        "uncovered ops")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on warnings too")
    p.add_argument("--disable", default="",
                   help="comma-separated rule ids to mute")
    p.add_argument("--shapes", default="",
                   help="arg shapes for graph lint, e.g. "
                        "\"data=(1,3,224,224)\"")
    p.add_argument("--no-consts", action="store_true",
                   help="skip the trace-based large-constant check")
    p.add_argument("--serving", action="store_true",
                   help="with a .json symbol target: also run the SRV "
                        "serving rules (recompile-free bucket execution; "
                        "needs --shapes for the batch-polymorphism probe)")
    args = p.parse_args(argv)

    from . import (self_check, lint_file, lint_symbol, lint_serving,
                   generate_coverage_md, render_text, render_json,
                   exit_code)
    disable = tuple(r.strip() for r in args.disable.split(",") if r.strip())

    if args.coverage:
        rows, uncovered = generate_coverage_md()
        n = len(rows)
        print("OP_COVERAGE.md: %d ops, %d uncovered" % (n, len(uncovered)))
        for name in uncovered:
            print("  NOT COVERED: %s" % name)
        return 1 if uncovered else 0

    if args.self_check:
        findings = self_check(disable=disable)
        print(render_json(findings) if args.as_json
              else render_text(findings, title="mxlint --self-check"))
        # the shipped registry must be clean: warnings fail too
        return exit_code(findings, strict=True)

    if not args.target:
        p.error("give a target script/symbol, --self-check, or --coverage")

    if args.target.endswith(".json"):
        from ..symbol import load
        sym = load(args.target)
        shapes = _parse_shapes(args.shapes)
        findings = lint_symbol(sym, shapes=shapes, disable=disable,
                               check_consts=not args.no_consts)
        if args.serving:
            findings += lint_serving(sym, data_shapes=shapes,
                                     disable=disable)
        title = "mxlint graph %s" % args.target
    else:
        findings = lint_file(args.target, disable=disable)
        title = "mxlint source %s" % args.target
    print(render_json(findings) if args.as_json
          else render_text(findings, title=title))
    return exit_code(findings, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
