"""Budget models: fixed model/step programs whose modeled cost is gated
by the checked-in ``STATIC_BUDGETS.json``.

Each builder constructs a model at a pinned geometry, runs the static
cost pass (:mod:`.cost`) and, for training steps, the DST distributed
lint (:mod:`.dist_lint`) — all hardware-free: meshes are pinned to one
CPU device (``jax.devices("cpu")``, present even when the TPU backend is
unreachable) and the data-axis size is *declared* (``DECLARED_AXIS``)
through ``make_jaxpr(axis_env=...)``, so the numbers are identical on
the 1-core CI host, the 8-virtual-device test mesh, and a TPU pod.

``python -m mxnet_tpu.analysis --cost --budget STATIC_BUDGETS.json``
re-analyzes every budgeted model and fails CI (COST001) when a PR blows
a metric past tolerance — a doubled step FLOP count or a widened
host→device transfer is caught with no accelerator attached.
``tools/update_budgets.py`` regenerates the file when a change is
intentional.
"""
from __future__ import annotations

__all__ = ["BUDGET_MODELS", "build_model", "DECLARED_AXIS",
           "BUDGET_METRICS"]

# the data-axis size every trainer model is analyzed at (collective
# bytes depend on it; declared, not discovered, for determinism)
DECLARED_AXIS = 8

# metrics a STATIC_BUDGETS.json row may pin, in gate order
BUDGET_METRICS = ("flops", "transcendentals", "transfer_bytes",
                  "peak_hbm_bytes", "collective_bytes")


def _cpu_mesh():
    import jax

    from ..parallel import mesh as mesh_mod
    return mesh_mod.make_mesh((1,), ("data",), [jax.devices("cpu")[0]])


def _mlp_block():
    from .. import init as mx_init
    from ..gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx_init.Xavier())
    return net


def mlp_train_step():
    """DataParallelTrainer step over a 2-layer MLP, batch 64x16."""
    from ..gluon import loss as gloss
    from ..parallel.trainer import DataParallelTrainer
    trainer = DataParallelTrainer(
        _mlp_block(), gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=_cpu_mesh())
    report = trainer.cost_report(data_shape=(64, 16), label_shape=(64,),
                                 declared_axis_size=DECLARED_AXIS)
    findings = trainer.lint(data_shape=(64, 16), label_shape=(64,),
                            declared_axis_size=DECLARED_AXIS)
    return report, findings


def mlp_infer():
    """Symbolic MLP forward (FC-relu-FC-softmax), batch 8x16."""
    from .. import symbol as sym
    from .cost import analyze_symbol
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=64, name="bm_fc1")
    a = sym.Activation(h, act_type="relu", name="bm_relu")
    out = sym.FullyConnected(a, num_hidden=10, name="bm_fc2")
    net = sym.SoftmaxOutput(out, name="bm_softmax")
    report = analyze_symbol(net, shapes={"data": (8, 16)})
    if report is None:
        raise RuntimeError("mlp_infer symbol did not trace")
    return report, []


def convnet_infer():
    """Small conv net (conv-bn-relu-pool-fc), NCHW batch 4x3x32x32 —
    exercises the conv/reduce_window cost paths."""
    from .. import symbol as sym
    from .cost import analyze_symbol
    data = sym.var("data")
    c = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                        no_bias=True, name="bm_conv")
    b = sym.BatchNorm(c, fix_gamma=False, name="bm_bn")
    r = sym.Activation(b, act_type="relu", name="bm_crelu")
    p = sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="bm_pool")
    f = sym.Flatten(p, name="bm_flat")
    out = sym.FullyConnected(f, num_hidden=10, name="bm_cfc")
    net = sym.SoftmaxOutput(out, name="bm_csoftmax")
    report = analyze_symbol(net, shapes={"data": (4, 3, 32, 32)})
    if report is None:
        raise RuntimeError("convnet_infer symbol did not trace")
    return report, []


def resnet50_train_step():
    """ResNet-50 NHWC training step at the bench geometry (batch 32/chip
    — FLOPs scale linearly in batch, so flops/img is batch-free).  Heavy
    (~half a minute of tracing on the 1-core host): used by the bench
    ``static_cost`` stage and on-demand, NOT in STATIC_BUDGETS.json."""
    from .. import init as mx_init
    from ..gluon import loss as gloss
    from ..gluon.model_zoo import vision
    from ..parallel.trainer import DataParallelTrainer
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize(mx_init.Xavier())
    trainer = DataParallelTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9}, mesh=_cpu_mesh())
    report = trainer.cost_report(data_shape=(32, 224, 224, 3),
                                 label_shape=(32,),
                                 declared_axis_size=DECLARED_AXIS)
    findings = trainer.lint(data_shape=(32, 224, 224, 3),
                            label_shape=(32,),
                            declared_axis_size=DECLARED_AXIS)
    return report, findings


BUDGET_MODELS = {
    "mlp_train_step": mlp_train_step,
    "mlp_infer": mlp_infer,
    "convnet_infer": convnet_infer,
    "resnet50_train_step": resnet50_train_step,
}


def build_model(name):
    """(CostReport, [Finding]) for one registered budget model."""
    if name not in BUDGET_MODELS:
        raise KeyError("unknown budget model %r (have: %s)"
                       % (name, ", ".join(sorted(BUDGET_MODELS))))
    return BUDGET_MODELS[name]()


def compute_budgets(models=None):
    """{model: {metric: value}} for the given (default: all non-heavy)
    budget models — what ``tools/update_budgets.py`` writes."""
    out = {}
    for name in sorted(models if models is not None
                       else [m for m in BUDGET_MODELS
                             if m != "resnet50_train_step"]):
        report, _ = build_model(name)
        d = report.as_dict()
        out[name] = {m: int(d[m]) for m in BUDGET_METRICS}
    return out


def check_budgets(budget_path, tolerance_pct=None):
    """Gate the budget file: rebuild every budgeted model, compare each
    pinned metric within tolerance, and fold in the models' own DST
    findings.  Returns (findings, {model: CostReport})."""
    import json

    from .findings import Finding

    with open(budget_path) as f:
        budget = json.load(f)
    tol = float(tolerance_pct if tolerance_pct is not None
                else budget.get("tolerance_pct", 10)) / 100.0
    findings, reports = [], {}
    budgeted = budget.get("models", {})
    for name in sorted(budgeted):
        row = budgeted[name]
        if name not in BUDGET_MODELS:
            findings.append(Finding(
                "COST001", name,
                "STATIC_BUDGETS.json pins %r but no such budget model "
                "is registered — the gate is checking nothing; remove "
                "the row or restore the model" % (name,)))
            continue
        try:
            report, dst = build_model(name)
        except Exception as e:
            findings.append(Finding(
                "COST001", name,
                "budget model %r no longer builds: %s: %s"
                % (name, type(e).__name__, str(e)[:200])))
            continue
        reports[name] = report
        findings += dst
        d = report.as_dict()
        for metric in BUDGET_METRICS:
            if metric not in row:
                continue
            want, got = float(row[metric]), float(d[metric])
            if want == 0 and got == 0:
                continue
            hi = want * (1 + tol)
            lo = want * (1 - tol)
            if got > hi:
                findings.append(Finding(
                    "COST001", "%s.%s" % (name, metric),
                    "modeled %s of %s is %d, %.1f%% over the budget %d "
                    "(tolerance %.0f%%) — a regression, or regenerate "
                    "via tools/update_budgets.py if intentional"
                    % (metric, name, int(got),
                       (got / want - 1) * 100 if want else 0.0,
                       int(want), tol * 100)))
            elif got < lo:
                findings.append(Finding(
                    "COST002", "%s.%s" % (name, metric),
                    "modeled %s of %s is %d, %.1f%% under the budget %d "
                    "— bank the improvement: tools/update_budgets.py"
                    % (metric, name, int(got),
                       (1 - got / want) * 100 if want else 0.0,
                       int(want))))
    for name in sorted(set(BUDGET_MODELS) - set(budgeted)
                       - {"resnet50_train_step"}):
        findings.append(Finding(
            "COST002", name,
            "budget model %r has no STATIC_BUDGETS.json row — it is "
            "not gated; add it via tools/update_budgets.py" % (name,)))
    return findings, reports
