"""Budget models: fixed model/step programs whose modeled cost is gated
by the checked-in ``STATIC_BUDGETS.json``.

Each builder constructs a model at a pinned geometry, runs the static
cost pass (:mod:`.cost`) and, for training steps, the DST distributed
lint (:mod:`.dist_lint`) — all hardware-free: meshes are pinned to one
CPU device (``jax.devices("cpu")``, present even when the TPU backend is
unreachable) and the data-axis size is *declared* (``DECLARED_AXIS``)
through ``make_jaxpr(axis_env=...)``, so the numbers are identical on
the 1-core CI host, the 8-virtual-device test mesh, and a TPU pod.

``python -m mxnet_tpu.analysis --cost --budget STATIC_BUDGETS.json``
re-analyzes every budgeted model and fails CI (COST001) when a PR blows
a metric past tolerance — a doubled step FLOP count or a widened
host→device transfer is caught with no accelerator attached.
``tools/update_budgets.py`` regenerates the file when a change is
intentional.
"""
from __future__ import annotations

__all__ = ["BUDGET_MODELS", "build_model", "DECLARED_AXIS",
           "BUDGET_METRICS"]

# the data-axis size every trainer model is analyzed at (collective
# bytes depend on it; declared, not discovered, for determinism)
DECLARED_AXIS = 8

# metrics a STATIC_BUDGETS.json row may pin, in gate order
BUDGET_METRICS = ("flops", "transcendentals", "transfer_bytes",
                  "peak_hbm_bytes", "collective_bytes")


def _cpu_mesh():
    import jax

    from ..parallel import mesh as mesh_mod
    return mesh_mod.make_mesh((1,), ("data",), [jax.devices("cpu")[0]])


def _mlp_block():
    from .. import init as mx_init
    from ..gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx_init.Xavier())
    return net


def mlp_train_step():
    """DataParallelTrainer step over a 2-layer MLP, batch 64x16."""
    from ..gluon import loss as gloss
    from ..parallel.trainer import DataParallelTrainer
    trainer = DataParallelTrainer(
        _mlp_block(), gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=_cpu_mesh())
    report = trainer.cost_report(data_shape=(64, 16), label_shape=(64,),
                                 declared_axis_size=DECLARED_AXIS)
    findings = trainer.lint(data_shape=(64, 16), label_shape=(64,),
                            declared_axis_size=DECLARED_AXIS)
    return report, findings


def mlp_infer():
    """Symbolic MLP forward (FC-relu-FC-softmax), batch 8x16."""
    from .. import symbol as sym
    from .cost import analyze_symbol
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=64, name="bm_fc1")
    a = sym.Activation(h, act_type="relu", name="bm_relu")
    out = sym.FullyConnected(a, num_hidden=10, name="bm_fc2")
    net = sym.SoftmaxOutput(out, name="bm_softmax")
    report = analyze_symbol(net, shapes={"data": (8, 16)})
    if report is None:
        raise RuntimeError("mlp_infer symbol did not trace")
    return report, []


def convnet_infer():
    """Small conv net (conv-bn-relu-pool-fc), NCHW batch 4x3x32x32 —
    exercises the conv/reduce_window cost paths."""
    from .. import symbol as sym
    from .cost import analyze_symbol
    data = sym.var("data")
    c = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                        no_bias=True, name="bm_conv")
    b = sym.BatchNorm(c, fix_gamma=False, name="bm_bn")
    r = sym.Activation(b, act_type="relu", name="bm_crelu")
    p = sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="bm_pool")
    f = sym.Flatten(p, name="bm_flat")
    out = sym.FullyConnected(f, num_hidden=10, name="bm_cfc")
    net = sym.SoftmaxOutput(out, name="bm_csoftmax")
    report = analyze_symbol(net, shapes={"data": (4, 3, 32, 32)})
    if report is None:
        raise RuntimeError("convnet_infer symbol did not trace")
    return report, []


def resnet50_train_step():
    """ResNet-50 NHWC training step at the bench geometry (batch 32/chip
    — FLOPs scale linearly in batch, so flops/img is batch-free).  Heavy
    (~half a minute of tracing on the 1-core host): used by the bench
    ``static_cost`` stage and on-demand, NOT in STATIC_BUDGETS.json."""
    from .. import init as mx_init
    from ..gluon import loss as gloss
    from ..gluon.model_zoo import vision
    from ..parallel.trainer import DataParallelTrainer
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize(mx_init.Xavier())
    trainer = DataParallelTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9}, mesh=_cpu_mesh())
    report = trainer.cost_report(data_shape=(32, 224, 224, 3),
                                 label_shape=(32,),
                                 declared_axis_size=DECLARED_AXIS)
    findings = trainer.lint(data_shape=(32, 224, 224, 3),
                            label_shape=(32,),
                            declared_axis_size=DECLARED_AXIS)
    return report, findings


def zero1_mlp_train_step():
    """ZeRO-1 sharded weight update (arxiv 2004.13336) as a static
    proof: the per-replica spelling reduce-scatters the flat gradient
    over a declared 8-way data axis, updates a 1/8-sized momentum
    shard, and all-gathers the new params.  The budget row pins its
    peak HBM; the builder additionally proves the ZeRO-1 relation —
    modeled peak must come in at least optimizer-state-bytes x
    (1 - 1/8) below the replicated twin (the reduce-scatter spelling
    saves more: the post-reduction gradient buffer is 1/8-sized too,
    so the exact modeled drop is reported in the shard extras) — and
    runs the mixed-axis DST lint, so a deleted all-gather fails the
    gate with DST007 named."""
    import jax

    from . import shard_fixtures as sf
    from . import shard_prop as sp
    from .cost import analyze_jaxpr, unpriced_findings
    from .findings import Finding

    k = DECLARED_AXIS
    mesh = sp.MeshSpec({"data": k})
    step, args = sf.zero1_step_program(k)
    closed = jax.make_jaxpr(step, axis_env=[("data", k)])(*args)
    n_train = len(args[0])
    # flat invars: train leaves, m_state, x, y — the batch is host-fed
    host = [n_train + 1, n_train + 2]
    report = analyze_jaxpr(closed, axis_sizes={"data": k},
                           host_invars=host)
    report.transfer_d2h_bytes = 4    # only the loss comes back

    findings = sp.lint_sharded_step(
        closed, mesh, data_axes=("data",),
        varying_invars=host,
        shard_dims={n_train: {0: ("data",)}},    # momentum shard
        param_outvars=list(range(1, 1 + n_train)),
        param_names=["w1", "b1", "w2", "b2", "w3", "b3"],
        subject="zero1_mlp_train_step")
    findings += unpriced_findings(report, subject="zero1_mlp_train_step")

    # the memory proof against the replicated twin (same step, full
    # optimizer state, plain pmean — what the trainer does today)
    twin_step, twin_args = sf.zero1_step_program(
        k, shard_state=False, all_gather=True)
    twin_closed = jax.make_jaxpr(
        twin_step, axis_env=[("data", k)])(*twin_args)
    twin = analyze_jaxpr(twin_closed, axis_sizes={"data": k},
                         host_invars=host)
    state_bytes = sf.zero1_state_bytes(k)
    floor = state_bytes * (k - 1) // k
    drop = twin.peak_hbm_bytes - report.peak_hbm_bytes
    if drop < floor:
        findings.append(Finding(
            "COST001", "zero1_mlp_train_step.peak_hbm_bytes",
            "ZeRO-1 proof violated: modeled peak HBM is only %d bytes "
            "below the replicated twin (%d vs %d) — the sharded update "
            "must save at least optimizer-state-bytes x (1 - 1/%d) = "
            "%d bytes (arxiv 2004.13336); the optimizer state is no "
            "longer sharded" % (drop, report.peak_hbm_bytes,
                                twin.peak_hbm_bytes, k, floor)))

    shard = sp.collective_schedule(closed, mesh,
                                   subject="zero1_mlp_train_step")
    shard.extras.update({
        "zero1_peak_hbm_bytes": int(report.peak_hbm_bytes),
        "replicated_twin_peak_hbm_bytes": int(twin.peak_hbm_bytes),
        "optimizer_state_bytes": int(state_bytes),
        "zero1_floor_bytes": int(floor),
        "modeled_hbm_drop_bytes": int(drop),
        "modeled_zero1_hbm_drop_pct": round(
            100.0 * drop / twin.peak_hbm_bytes, 2)
        if twin.peak_hbm_bytes else 0.0,
    })
    # the RUNTIME half (ISSUE 13): the real DataParallelTrainer(zero=1)
    # step tape must satisfy the same budget — parity with the fixture
    # the row pins, the ZeRO-1 HBM relation against its own per-replica
    # twin, the mixed-axis DST lint (a deleted runtime all-gather is
    # DST007 -> rc 2) and reduce-scatter/all-gather byte parity with the
    # collectives the global-view mxshard pass infers for the
    # replicated spelling
    rt_findings, rt_extras = zero1_runtime_checks(report)
    findings += rt_findings
    shard.extras.update(rt_extras)
    return report, findings, shard


def _zero1_geometry_trainer(zero, dtype="float32"):
    """A real ``DataParallelTrainer`` at the pinned ``ZERO1_GEOMETRY``
    (the fixture's 3-layer MLP), on the 1-cpu-device mesh — hardware-
    free analysis subject for the runtime half of the ZeRO-1 proof
    (and, with ``dtype="bf16"``, of the mixed-precision one)."""
    import jax

    from .. import init as mx_init
    from ..gluon import loss as gloss
    from ..gluon import nn
    from ..parallel.trainer import DataParallelTrainer
    from . import shard_fixtures as sf

    g = sf.ZERO1_GEOMETRY
    net = nn.HybridSequential()
    for h in g["hidden"]:
        net.add(nn.Dense(h, activation="relu"))
    net.add(nn.Dense(g["classes"]))
    net.initialize(mx_init.Xavier())
    return DataParallelTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": g["lr"], "momentum": g["momentum"]},
        mesh=_cpu_mesh(), zero=zero, dtype=dtype)


def zero1_runtime_checks(fixture_report, tolerance_pct=10.0):
    """Gate the zero=1 trainer's REAL step tape against the
    ``zero1_mlp_train_step`` budget: ``(findings, extras)``.

    - the runtime DST/mixed-axis lint (``trainer.zero_report``): a
      deleted runtime all-gather (``parallel/zero.py``'s
      ``ZERO1_RUNTIME_ALL_GATHER`` seam) fails here with DST007;
    - flops/transcendentals/transfer/collective parity with the fixture
      the budget row pins (two-sided, the gate tolerance) and peak HBM
      no worse than the fixture's (one-sided: the runtime spelling
      donates tighter and is allowed to be better);
    - the ZeRO-1 relation on the runtime pair: modeled peak HBM at
      least optimizer-state x (1 - 1/K) below the trainer's OWN
      per-replica replicated twin;
    - reduce-scatter + all-gather wire bytes equal to the gradient
      psum bytes the global-view mxshard pass infers for the
      replicated spelling, up to the flat-padding ring bytes.
    """
    import jax
    import jax.numpy as jnp

    from . import shard_fixtures as sf
    from .cost import analyze_fn
    from .findings import Finding

    g = sf.ZERO1_GEOMETRY
    k = DECLARED_AXIS
    tol = float(tolerance_pct) / 100.0
    data_shape = (g["batch"] * k, g["in_dim"])
    label_shape = (g["batch"] * k,)
    findings = []

    trainer = _zero1_geometry_trainer(zero=1)
    rt_report, rt_findings, rt_shard = trainer.zero_report(
        data_shape=data_shape, label_shape=label_shape,
        label_dtype="int32", declared_axis_size=k)
    findings += rt_findings

    # metric parity with the fixture (== the pinned budget row)
    fx = fixture_report.as_dict()
    rt = rt_report.as_dict()
    for metric in ("flops", "transcendentals", "transfer_bytes",
                   "collective_bytes"):
        want, got = float(fx[metric]), float(rt[metric])
        if want and abs(got - want) > tol * want:
            findings.append(Finding(
                "COST001", "zero1_mlp_train_step.runtime.%s" % metric,
                "the zero=1 trainer's REAL step tape models %s = %d "
                "but the budgeted fixture pins %d (tolerance %.0f%%): "
                "the runtime and the proven spelling have drifted "
                "apart" % (metric, int(got), int(want), tol * 100)))
    if rt["peak_hbm_bytes"] > fx["peak_hbm_bytes"] * (1 + tol):
        findings.append(Finding(
            "COST001", "zero1_mlp_train_step.runtime.peak_hbm_bytes",
            "the zero=1 trainer's REAL step models peak HBM %d, over "
            "the budgeted fixture's %d (tolerance %.0f%%) — the "
            "runtime lost the ZeRO-1 memory story"
            % (int(rt["peak_hbm_bytes"]), int(fx["peak_hbm_bytes"]),
               tol * 100)))

    # the ZeRO-1 relation against the trainer's own per-replica twin
    twin = _zero1_geometry_trainer(zero=0)
    train_vals = None
    try:
        import numpy as _onp

        from ..ndarray import NDArray
        x0 = NDArray(jnp.zeros(data_shape, _onp.float32))
        y0 = NDArray(jnp.zeros(label_shape, _onp.int32))
        twin._setup(x0, y0)
        train_vals = tuple(twin._params_by_name[n].data()._data
                           for n in twin._train_names)
        aux_vals = tuple(twin._params_by_name[n].data()._data
                         for n in twin._aux_names)
        states = tuple(twin._states_raw)
        xs = jax.ShapeDtypeStruct((g["batch"], g["in_dim"]),
                                  _onp.float32)
        ys = jax.ShapeDtypeStruct((g["batch"],), _onp.int32)
        key = jax.ShapeDtypeStruct((2,), _onp.uint32)
        twin_rep = analyze_fn(
            twin._build_replica_step(), train_vals, states, aux_vals,
            xs, ys, key, jnp.float32(0.01), jnp.int32(1),
            axis_env=[("data", k)], donate_argnums=(0, 1),
            host_argnums=(3, 4))
    except Exception as e:
        findings.append(Finding(
            "COST001", "zero1_mlp_train_step.runtime",
            "the replicated twin of the runtime ZeRO-1 proof no longer "
            "traces: %s: %s" % (type(e).__name__, str(e)[:200])))
        return findings, {}

    state_bytes = sf.zero1_state_bytes(k)
    floor = state_bytes * (k - 1) // k
    drop = twin_rep.peak_hbm_bytes - rt_report.peak_hbm_bytes
    if drop < floor:
        findings.append(Finding(
            "COST001", "zero1_mlp_train_step.runtime.peak_hbm_bytes",
            "ZeRO-1 runtime proof violated: the zero=1 trainer's "
            "modeled peak HBM is only %d bytes below its replicated "
            "twin (%d vs %d) — the sharded update must save at least "
            "optimizer-state-bytes x (1 - 1/%d) = %d bytes; the "
            "optimizer state is no longer sharded at runtime"
            % (drop, rt_report.peak_hbm_bytes, twin_rep.peak_hbm_bytes,
               k, floor)))

    # collective-byte parity with the global-view mxshard pass: the
    # explicit rs+ag pair must carry what GSPMD's inferred gradient
    # psum would, up to the flat zero-padding's ring bytes
    global_view = twin.shard_report(
        data_shape=data_shape, label_shape=label_shape,
        label_dtype="int32", declared_axis_size=k)
    inferred = sum(ev.wire_bytes for ev in global_view.schedule
                   if ev.inferred)
    rs_ag = sum(ev.wire_bytes for ev in rt_shard.schedule
                if ev.prim in ("reduce_scatter", "all_gather"))
    pad_ring = 2 * (k - 1) * ((rt_shard.extras.get("zero1_plan") or {})
                              .get("padded", 0)
                              - (rt_shard.extras.get("zero1_plan") or {})
                              .get("total", 0)) * 4 // max(k, 1)
    slack = max(64, pad_ring)
    if abs(rs_ag - inferred) > slack:
        findings.append(Finding(
            "COST001", "zero1_mlp_train_step.runtime.collective_bytes",
            "runtime reduce-scatter+all-gather wire bytes (%d) do not "
            "match the gradient psum the global-view mxshard pass "
            "infers for the replicated spelling (%d, slack %d): the "
            "ZeRO-1 pair moves different bytes than the collective it "
            "replaces" % (rs_ag, inferred, slack)))

    extras = {
        "runtime_zero1_peak_hbm_bytes": int(rt_report.peak_hbm_bytes),
        "runtime_twin_peak_hbm_bytes": int(twin_rep.peak_hbm_bytes),
        "runtime_hbm_drop_bytes": int(drop),
        "runtime_zero1_hbm_drop_pct": round(
            100.0 * drop / twin_rep.peak_hbm_bytes, 2)
        if twin_rep.peak_hbm_bytes else 0.0,
        "runtime_rs_ag_bytes": int(rs_ag),
        "runtime_inferred_psum_bytes": int(inferred),
    }
    return findings, extras


# Pinned ceilings for the mixed-precision ZeRO-1 proof: measured at
# ZERO1_GEOMETRY, the bf16 trainer models peak HBM at 0.660x its f32
# twin (the 34% drop docs/precision.md claims: bf16 params, activations
# and all-gather, f32 masters only as the 1/K shard) and collective
# bytes at 0.750x (the all-gather halves; the gradient reduce-scatter
# deliberately stays f32 — the tightened DST004 contract).  The
# ceilings sit above the measured ratios with margin but BELOW the
# broken spellings: re-deriving masters from a full flat f32 vector
# per rank (the PRECISION_MASTER_F32 seam) models 0.769x and fails.
BF16_PEAK_HBM_RATIO_CEILING = 0.70
BF16_COLLECTIVE_RATIO_CEILING = 0.78


def bf16_zero1_train_step():
    """Mixed-precision ZeRO-1 (docs/precision.md) as a static proof:
    the real ``DataParallelTrainer(dtype="bf16", zero=1)`` step tape at
    the pinned ``ZERO1_GEOMETRY``, gated three ways —

    - the runtime DST/mixed-axis lint: the gradient reduce-scatter must
      run f32 (``PRECISION_F32_GRAD_REDUCE`` flipped = a bf16 ring
      reduction = the tightened DST004, rc 2);
    - modeled peak HBM at most ``BF16_PEAK_HBM_RATIO_CEILING`` x the
      f32 twin's: holds only while the f32 masters exist solely as the
      1/K shard — ``PRECISION_MASTER_F32`` flipped re-derives them from
      a full per-rank flat f32 vector and busts the ceiling (rc 2);
    - modeled collective bytes at most
      ``BF16_COLLECTIVE_RATIO_CEILING`` x the twin's: the param
      all-gather must move bf16 on the wire.

    The budget row pins the bf16 tape's absolute metrics; the ratios
    ride the shard extras."""
    import jax

    from . import shard_fixtures as sf
    from .findings import Finding

    k = DECLARED_AXIS
    g = sf.ZERO1_GEOMETRY
    data_shape = (g["batch"] * k, g["in_dim"])
    label_shape = (g["batch"] * k,)

    trainer = _zero1_geometry_trainer(zero=1, dtype="bf16")
    report, findings, shard = trainer.zero_report(
        data_shape=data_shape, label_shape=label_shape,
        label_dtype="int32", declared_axis_size=k)

    # the f32 twin: same geometry, same ZeRO-1 spelling, full precision
    # (its own gate lives in zero1_mlp_train_step — only the ratio is
    # this row's business)
    twin = _zero1_geometry_trainer(zero=1, dtype="float32")
    twin_report, _, _ = twin.zero_report(
        data_shape=data_shape, label_shape=label_shape,
        label_dtype="int32", declared_axis_size=k)

    peak_ratio = report.peak_hbm_bytes / max(twin_report.peak_hbm_bytes,
                                             1)
    coll_ratio = report.collective_bytes / max(
        twin_report.collective_bytes, 1)
    if peak_ratio > BF16_PEAK_HBM_RATIO_CEILING:
        findings.append(Finding(
            "COST001", "bf16_zero1_train_step.peak_hbm_bytes",
            "mixed-precision proof violated: the bf16 ZeRO-1 step "
            "models peak HBM at %.3fx its f32 twin (%d vs %d bytes), "
            "over the %.2f ceiling — the f32 masters are no longer "
            "confined to the 1/%d shard (or the params/activations "
            "stopped being bf16)"
            % (peak_ratio, report.peak_hbm_bytes,
               twin_report.peak_hbm_bytes,
               BF16_PEAK_HBM_RATIO_CEILING, k)))
    if coll_ratio > BF16_COLLECTIVE_RATIO_CEILING:
        findings.append(Finding(
            "COST001", "bf16_zero1_train_step.collective_bytes",
            "mixed-precision proof violated: the bf16 ZeRO-1 step "
            "models collective bytes at %.3fx its f32 twin (%d vs %d), "
            "over the %.2f ceiling — the param all-gather is no longer "
            "moving bf16 on the wire"
            % (coll_ratio, report.collective_bytes,
               twin_report.collective_bytes,
               BF16_COLLECTIVE_RATIO_CEILING)))

    shard.extras.update({
        "bf16_peak_hbm_bytes": int(report.peak_hbm_bytes),
        "f32_twin_peak_hbm_bytes": int(twin_report.peak_hbm_bytes),
        "bf16_peak_hbm_ratio": round(peak_ratio, 4),
        "bf16_collective_bytes": int(report.collective_bytes),
        "f32_twin_collective_bytes": int(twin_report.collective_bytes),
        "bf16_collective_ratio": round(coll_ratio, 4),
        "bf16_modeled_hbm_drop_pct": round(100.0 * (1 - peak_ratio), 2),
    })
    return report, findings, shard


def ring_attention_fwd():
    """The shipped ring attention (forward + backward) on a declared
    8-way ``sequence`` axis: proves the ppermute schedule — 6 rotating
    buffers (K/V forward; K/V + dK/dV accumulators backward) x K hops x
    chunk bytes — against the closed-form ring formula (DST009) and
    pins the modeled collective bytes."""
    import jax

    from . import shard_fixtures as sf
    from . import shard_prop as sp
    from .cost import analyze_jaxpr, unpriced_findings
    from .findings import Finding

    k = 8
    mesh = sp.MeshSpec({"sequence": k})
    fn, args = sf.ring_attention_program(k=k)
    closed = jax.make_jaxpr(fn, axis_env=[("sequence", k)])(*args)
    report = analyze_jaxpr(closed, axis_sizes={"sequence": k},
                           host_invars=[])
    shard = sp.collective_schedule(closed, mesh,
                                   subject="ring_attention_fwd")
    findings = sp.lint_ring_schedule(closed, "sequence", k,
                                     subject="ring_attention_fwd")
    findings += sp.lint_sharded_step(
        closed, mesh, data_axes=("sequence",),
        varying_invars=[0, 1, 2],
        shard_dims={i: {1: ("sequence",)} for i in range(3)},
        param_outvars=[], subject="ring_attention_fwd")
    findings += unpriced_findings(report, subject="ring_attention_fwd")

    # closed-form cross-check: 6 rotating buffers x K hops x chunk
    b, tl, h, d = args[0].shape
    chunk = b * tl * h * d * 4
    formula = 6 * k * chunk
    if shard.collective_bytes != formula:
        findings.append(Finding(
            "DST009", "ring_attention_fwd",
            "modeled ring-attention collective bytes %d do not match "
            "the closed-form ring formula %d (= 6 buffers x %d hops x "
            "%d-byte chunk): the schedule lost or duplicated a "
            "rotation" % (shard.collective_bytes, formula, k, chunk)))
    shard.extras.update({
        "modeled_ring_attn_collective_bytes": int(shard.collective_bytes),
        "ring_formula_bytes": int(formula),
        "chunk_bytes": int(chunk),
        "hops": int(k),
    })
    return report, findings, shard


def ulysses_attention():
    """The shipped Ulysses all-to-all attention (forward + backward) on
    a declared 8-way ``sequence`` axis: pins the all_to_all wire bytes
    and DST-checks the swap-back pair — the traced program must carry
    exactly 4 sequence→head and 4 head→sequence reshards (3 inputs + 1
    output, each direction mirrored in the VJP) whose bytes match the
    closed-form (K-1)/K × payload formula."""
    import jax

    from . import shard_fixtures as sf
    from . import shard_prop as sp
    from .cost import analyze_jaxpr, unpriced_findings
    from .findings import Finding

    k = 8
    mesh = sp.MeshSpec({"sequence": k})
    fn, args = sf.ulysses_attention_program(k=k)
    closed = jax.make_jaxpr(fn, axis_env=[("sequence", k)])(*args)
    report = analyze_jaxpr(closed, axis_sizes={"sequence": k},
                           host_invars=[])
    shard = sp.collective_schedule(closed, mesh,
                                   subject="ulysses_attention")
    findings = sp.lint_sharded_step(
        closed, mesh, data_axes=("sequence",),
        varying_invars=[0, 1, 2],
        shard_dims={i: {1: ("sequence",)} for i in range(3)},
        param_outvars=[], subject="ulysses_attention")
    findings += unpriced_findings(report, subject="ulysses_attention")

    # the swap-back pair proof: every seq→head reshard (the head-group
    # dim scatters out: split_axis > concat_axis in jax's canonicalized
    # untiled spelling) must be matched by a head→seq reshard
    # (split_axis < concat_axis), and fwd+bwd carries 4 of each;
    # direction read off the traced eqn params
    from .cost import build_tape as _bt
    s2h = h2s = 0
    tape = _bt(closed, axis_sizes={"sequence": k})
    for op in tape.ops:
        if op.prim != "all_to_all" or "sequence" not in op.axes:
            continue
        split = int(op.params.get("split_axis", -1))
        concat = int(op.params.get("concat_axis", -1))
        if split > concat:
            s2h += 1
        else:
            h2s += 1
    if s2h != 4 or h2s != 4:
        findings.append(Finding(
            "DST009", "ulysses_attention",
            "the Ulysses swap-back pair is broken: traced %d "
            "sequence→head and %d head→sequence all_to_all reshards "
            "(want 4+4: q/k/v in + output out, mirrored by the VJP) — "
            "an unpaired reshard leaves the output head-sharded or "
            "drops a gradient swap" % (s2h, h2s)))

    b, tl, h, d = args[0].shape
    payload = b * tl * h * d * 4
    formula = 8 * (k - 1) * payload // k
    if shard.collective_bytes != formula:
        findings.append(Finding(
            "DST009", "ulysses_attention",
            "modeled Ulysses collective bytes %d do not match the "
            "closed-form formula %d (= 8 all_to_alls x (K-1)/K x "
            "%d-byte payload): a reshard was lost or duplicated"
            % (shard.collective_bytes, formula, payload)))
    shard.extras.update({
        "ulysses_modeled_collective_bytes": int(shard.collective_bytes),
        "ulysses_formula_bytes": int(formula),
        "payload_bytes": int(payload),
        "seq2head_reshards": int(s2h),
        "head2seq_reshards": int(h2s),
    })
    return report, findings, shard


# the pinned tp_transformer_train_step geometry: a 2-layer transformer
# LM at data=2 × model=2 × sequence=2 (the acceptance-criteria mesh),
# small enough to trace in seconds on the 1-core CI host but with every
# collective class present: vocab-parallel embedding + loss psums and
# row-parallel psums over `model`, the ring attention ppermute schedule
# over `sequence`, and the grads pmean over `data × sequence`
TP_GEOMETRY = {
    "vocab_size": 64, "d_model": 32, "n_heads": 4, "n_layers": 2,
    "d_ff": 64, "seq_len": 64, "attention": "ring",
    "batch": 8, "data": 2, "model": 2, "sequence": 2,
    "momentum": 0.9, "lr": 0.1,
}


def _tp_plan_and_program():
    from ..parallel.mesh import MeshPlan
    from ..transformer import TransformerLM, TransformerLMConfig

    g = TP_GEOMETRY
    cfg = TransformerLMConfig(
        vocab_size=g["vocab_size"], d_model=g["d_model"],
        n_heads=g["n_heads"], n_layers=g["n_layers"], d_ff=g["d_ff"],
        seq_len=g["seq_len"], attention=g["attention"])
    plan = MeshPlan(data=g["data"], model=g["model"],
                    sequence=g["sequence"])
    return plan, TransformerLM(cfg).mesh_program(plan), TransformerLM(cfg)


def tp_transformer_train_step():
    """The 2-3D-mesh transformer train step (docs/transformer.md) as a
    static proof: the per-replica spelling of ``transformer/step.py``
    at the pinned ``TP_GEOMETRY`` — fixture optimizer is the inline
    SGD+momentum — traced hardware-free over the declared
    ``data=2 × model=2 × sequence=2`` mesh.  The budget row pins its
    metrics; the builder runs the mixed-axis DST lint (deleting the
    row-parallel output psum via ``transformer/layers.py``'s
    ``TP_ROW_PSUM`` seam fails the gate rc=2 with the pending
    partial-sum DST001 named per parameter), proves the ring attention
    schedule (DST009) over ``sequence``, and gates the REAL
    ``DataParallelTrainer(mesh_plan=...)`` runtime tape against the
    fixture (``tp_runtime_checks``, the PR-13 ``zero1_runtime_checks``
    pattern)."""
    import jax
    import jax.numpy as jnp

    from ..transformer import step as tstep
    from . import shard_prop as sp
    from .cost import analyze_jaxpr, unpriced_findings

    g = TP_GEOMETRY
    plan, program, _ = _tp_plan_and_program()
    mesh = sp.MeshSpec(plan.axis_sizes())
    n = len(program.param_names)
    counts = [1] * n     # one momentum leaf per parameter
    step = tstep.build_replica_step(
        program, tstep.sgd_momentum_update(g["momentum"]), counts)
    train_avals = tuple(
        jax.ShapeDtypeStruct(program.local_shape(nm), jnp.float32)
        for nm in program.param_names)
    state_avals = train_avals       # momentum mirrors each param shard
    b_local, t_local = program.local_batch_shape(g["batch"])
    xs = jax.ShapeDtypeStruct((b_local, t_local), jnp.int32)
    ys = jax.ShapeDtypeStruct((b_local, t_local), jnp.int32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    closed = jax.make_jaxpr(step, axis_env=plan.axis_env())(
        train_avals, state_avals, xs, ys, key,
        jnp.float32(g["lr"]), jnp.int32(1))

    host = [2 * n, 2 * n + 1]
    report = analyze_jaxpr(closed, axis_sizes=plan.axis_sizes(),
                           donated_invars=list(range(2 * n)),
                           host_invars=host)
    report.transfer_d2h_bytes = 4    # only the loss comes back

    shard_dims = {}
    for i, nm in enumerate(program.param_names):
        spec = program.partition_spec(nm)
        dims = {d: (e,) for d, e in enumerate(spec) if e is not None}
        if dims:
            shard_dims[i] = dims
            shard_dims[n + i] = dims
    findings = sp.lint_sharded_step(
        closed, mesh, data_axes=plan.batch_axes(),
        varying_invars=host, shard_dims=shard_dims,
        param_outvars=list(range(1, 1 + n)),
        param_names=list(program.param_names),
        subject="tp_transformer_train_step")
    findings += sp.lint_ring_schedule(
        closed, "sequence", plan.size("sequence"),
        subject="tp_transformer_train_step")
    findings += unpriced_findings(report,
                                  subject="tp_transformer_train_step")

    shard = sp.collective_schedule(closed, mesh,
                                   subject="tp_transformer_train_step")
    per_axis = shard.collective_bytes_per_axis
    shard.extras.update({
        "tp_geometry": dict(TP_GEOMETRY),
        "attention_mode": program.attention_mode,
        "tp_modeled_model_axis_bytes": int(per_axis.get("model", 0)),
        "tp_modeled_sequence_axis_bytes": int(
            per_axis.get("sequence", 0)),
        "tp_modeled_data_axis_bytes": int(per_axis.get("data", 0)),
    })
    # the RUNTIME half: the real DataParallelTrainer(mesh_plan=...)
    # tape must satisfy the same budget
    rt_findings, rt_extras = tp_runtime_checks(report, shard)
    findings += rt_findings
    shard.extras.update(rt_extras)
    return report, findings, shard


def tp_runtime_checks(fixture_report, fixture_shard,
                      tolerance_pct=10.0):
    """Gate the ``DataParallelTrainer(mesh_plan=...)`` REAL step tape
    against the ``tp_transformer_train_step`` fixture: the trainer's
    ``mesh_report`` (gluon ``sgd`` via ``functional_optimizer_update``
    instead of the fixture's inline rule) must match the pinned
    metrics within tolerance, carry the same mixed-axis DST-clean
    schedule, and move EXACTLY the fixture's per-axis collective bytes
    — the runtime and the proven spelling can never drift."""
    from ..parallel.mesh import MeshPlan
    from ..parallel.trainer import DataParallelTrainer
    from .findings import Finding

    g = TP_GEOMETRY
    tol = float(tolerance_pct) / 100.0
    plan, _, block = _tp_plan_and_program()
    findings = []
    try:
        trainer = DataParallelTrainer(
            block, None, "sgd",
            {"learning_rate": g["lr"], "momentum": g["momentum"]},
            mesh_plan=MeshPlan(data=g["data"], model=g["model"],
                               sequence=g["sequence"]))
        rt_report, rt_findings, rt_shard = trainer.mesh_report(
            data_shape=(g["batch"], g["seq_len"]))
    except Exception as e:
        findings.append(Finding(
            "COST001", "tp_transformer_train_step.runtime",
            "the mesh-tier trainer no longer traces: %s: %s"
            % (type(e).__name__, str(e)[:200])))
        return findings, {}
    findings += rt_findings

    fx = fixture_report.as_dict()
    rt = rt_report.as_dict()
    for metric in ("flops", "transcendentals", "transfer_bytes",
                   "collective_bytes"):
        want, got = float(fx[metric]), float(rt[metric])
        if want and abs(got - want) > tol * want:
            findings.append(Finding(
                "COST001", "tp_transformer_train_step.runtime.%s"
                % metric,
                "the mesh-tier trainer's REAL step tape models %s = %d "
                "but the budgeted fixture pins %d (tolerance %.0f%%): "
                "the runtime and the proven spelling have drifted "
                "apart" % (metric, int(got), int(want), tol * 100)))
    if rt["peak_hbm_bytes"] > fx["peak_hbm_bytes"] * (1 + tol):
        findings.append(Finding(
            "COST001", "tp_transformer_train_step.runtime.peak_hbm_bytes",
            "the mesh-tier trainer's REAL step models peak HBM %d, "
            "over the budgeted fixture's %d (tolerance %.0f%%)"
            % (int(rt["peak_hbm_bytes"]), int(fx["peak_hbm_bytes"]),
               tol * 100)))

    # per-axis collective parity is EXACT: both spellings run the same
    # program code, and the optimizer difference is collective-free
    fx_axis = fixture_shard.collective_bytes_per_axis
    rt_axis = rt_shard.collective_bytes_per_axis
    for axis in ("model", "sequence"):
        if fx_axis.get(axis, 0) != rt_axis.get(axis, 0):
            findings.append(Finding(
                "COST001",
                "tp_transformer_train_step.runtime.%s_axis_bytes" % axis,
                "runtime %s-axis collective bytes (%d) differ from the "
                "fixture's (%d): the trainer's step moves different "
                "wire traffic than the proven schedule"
                % (axis, rt_axis.get(axis, 0), fx_axis.get(axis, 0))))
    extras = {
        "runtime_peak_hbm_bytes": int(rt["peak_hbm_bytes"]),
        "runtime_collective_bytes": int(rt["collective_bytes"]),
        "runtime_model_axis_bytes": int(rt_axis.get("model", 0)),
        "runtime_sequence_axis_bytes": int(rt_axis.get("sequence", 0)),
    }
    return findings, extras


# the pinned pp_transformer_train_step geometry: a 4-layer transformer
# LM stage-partitioned over pipe=2 (2 blocks per stage), each stage
# TP-sharded over model=2, batch-replicated over data=2 (8 declared
# devices), running the microbatched 1F1B schedule at M=4 — modeled
# bubble fraction (K-1)/(K-1+M) = 1/5, per-hop ppermute payload one
# microbatch's residual activations (mb x t x d_model x 4 bytes)
PP_GEOMETRY = {
    "vocab_size": 64, "d_model": 32, "n_heads": 4, "n_layers": 4,
    "d_ff": 64, "seq_len": 64, "microbatches": 4,
    "batch": 8, "data": 2, "model": 2, "pipeline": 2,
    "momentum": 0.9, "lr": 0.1,
}


def _pp_plan_and_program():
    from ..parallel.mesh import MeshPlan
    from ..transformer import TransformerLM, TransformerLMConfig

    g = PP_GEOMETRY
    cfg = TransformerLMConfig(
        vocab_size=g["vocab_size"], d_model=g["d_model"],
        n_heads=g["n_heads"], n_layers=g["n_layers"], d_ff=g["d_ff"],
        seq_len=g["seq_len"], microbatches=g["microbatches"])
    plan = MeshPlan(data=g["data"], model=g["model"],
                    pipeline=g["pipeline"])
    return plan, TransformerLM(cfg).mesh_program(plan), TransformerLM(cfg)


def pp_transformer_train_step():
    """The pipeline-parallel transformer train step (docs/pipeline.md)
    as a static proof: the one ``parallel/pipeline.py`` spelling of the
    1F1B schedule at the pinned ``PP_GEOMETRY``, traced hardware-free
    over the declared ``pipe=2 x model=2 x data=2`` mesh.  The budget
    row pins its metrics; the builder runs the mixed-axis DST lint plus
    the two pipeline-specific rules — DST011 proves the schedule shape
    (two full single-cycle rings over ``pipe`` scanned exactly
    ``M + K - 1`` ticks, per-hop bytes equal to one microbatch's
    activations, peak HBM holding the in-flight stash) and DST012
    proves stage-local gradients are never reduced over ``pipe``
    (flipping ``parallel/pipeline.py``'s ``PP_GRAD_ACCUM`` seam fails
    the gate rc=2 with every stacked block parameter named) — and
    gates the REAL ``DataParallelTrainer(mesh_plan=...)`` runtime tape
    against the fixture (``pp_runtime_checks``)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from ..parallel import pipeline as pp
    from ..transformer import step as tstep
    from . import shard_prop as sp
    from .cost import analyze_jaxpr, build_tape, unpriced_findings
    from .findings import Finding

    g = PP_GEOMETRY
    plan, program, _ = _pp_plan_and_program()
    mesh = sp.MeshSpec(plan.axis_sizes())
    n = len(program.param_names)
    counts = [1] * n     # one momentum leaf per parameter
    step = tstep.build_replica_step(
        program, tstep.sgd_momentum_update(g["momentum"]), counts)
    train_avals = tuple(
        jax.ShapeDtypeStruct(program.local_shape(nm), jnp.float32)
        for nm in program.param_names)
    state_avals = train_avals       # momentum mirrors each param shard
    b_local, t_local = program.local_batch_shape(g["batch"])
    xs = jax.ShapeDtypeStruct((b_local, t_local), jnp.int32)
    ys = jax.ShapeDtypeStruct((b_local, t_local), jnp.int32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    closed = jax.make_jaxpr(step, axis_env=plan.axis_env())(
        train_avals, state_avals, xs, ys, key,
        jnp.float32(g["lr"]), jnp.int32(1))

    host = [2 * n, 2 * n + 1]
    report = analyze_jaxpr(closed, axis_sizes=plan.axis_sizes(),
                           donated_invars=list(range(2 * n)),
                           host_invars=host)
    report.transfer_d2h_bytes = 4    # only the loss comes back

    shard_dims = {}
    for i, nm in enumerate(program.param_names):
        spec = program.partition_spec(nm)
        dims = {d: (e,) if isinstance(e, str) else tuple(e)
                for d, e in enumerate(spec) if e is not None}
        if dims:
            shard_dims[i] = dims
            shard_dims[n + i] = dims
    findings = sp.lint_sharded_step(
        closed, mesh, data_axes=plan.batch_axes(),
        varying_invars=host, shard_dims=shard_dims,
        param_outvars=list(range(1, 1 + n)),
        param_names=list(program.param_names),
        subject="pp_transformer_train_step")

    k, m = g["pipeline"], g["microbatches"]
    ticks = pp.pipeline_ticks(k, m)
    hop_bytes = (b_local // m) * t_local * g["d_model"] * 4
    stash_bytes = b_local * t_local * g["d_model"] * 4
    pipe_sharded = [
        i for i, nm in enumerate(program.param_names)
        if any(e == "pipe" or (isinstance(e, tuple) and "pipe" in e)
               for e in program.partition_spec(nm))]
    findings += sp.lint_pipeline_step(
        closed, plan.axis_sizes(), m,
        stash_bytes=stash_bytes, peak_hbm_bytes=report.peak_hbm_bytes,
        param_outvars=list(range(1, 1 + n)),
        param_names=list(program.param_names),
        pipe_sharded=pipe_sharded,
        subject="pp_transformer_train_step")
    findings += unpriced_findings(report,
                                  subject="pp_transformer_train_step")

    # the per-hop byte pin: every scanned stage-boundary ppermute must
    # carry EXACTLY one microbatch's activations — a widened carry
    # (stashing extra state in the ring) silently multiplies the wire
    # traffic every tick
    tape = build_tape(closed, axis_sizes=plan.axis_sizes())
    for op in tape.ops:
        if op.prim != "ppermute" or "pipe" not in op.axes:
            continue
        payload = sum(
            int(np.prod(tape.avals[i].shape))
            * tape.avals[i].dtype.itemsize for i in op.in_ids)
        if payload != hop_bytes:
            findings.append(Finding(
                "DST011", "pp_transformer_train_step",
                "stage-boundary ppermute carries %d bytes per hop but "
                "the pinned per-hop payload is %d (= one microbatch's "
                "activations, mb x t x d_model x 4): the ring carry "
                "has widened and the modeled pipe-axis traffic no "
                "longer matches the schedule" % (payload, hop_bytes)))

    shard = sp.collective_schedule(closed, mesh,
                                   subject="pp_transformer_train_step")
    per_axis = shard.collective_bytes_per_axis
    shard.extras.update({
        "pp_geometry": dict(PP_GEOMETRY),
        "pp_modeled_bubble_frac": pp.bubble_fraction(k, m),
        "pp_microbatches": int(m),
        "pp_ticks": int(ticks),
        "pp_hop_bytes": int(hop_bytes),
        "pp_stash_bytes": int(stash_bytes),
        "pp_modeled_pipe_axis_bytes": int(per_axis.get("pipe", 0)),
        "pp_modeled_model_axis_bytes": int(per_axis.get("model", 0)),
        "pp_modeled_data_axis_bytes": int(per_axis.get("data", 0)),
    })
    # the RUNTIME half: the real DataParallelTrainer(mesh_plan=...)
    # tape must satisfy the same budget
    rt_findings, rt_extras = pp_runtime_checks(report, shard)
    findings += rt_findings
    shard.extras.update(rt_extras)
    return report, findings, shard


def pp_runtime_checks(fixture_report, fixture_shard,
                      tolerance_pct=10.0):
    """Gate the ``DataParallelTrainer(mesh_plan=...)`` REAL pipelined
    step tape against the ``pp_transformer_train_step`` fixture: the
    trainer's ``mesh_report`` must match the pinned metrics within
    tolerance, carry the same DST-clean 1F1B schedule, move EXACTLY
    the fixture's per-axis collective bytes over ``pipe`` and
    ``model``, and report the same per-hop payload and bubble fraction
    — the runtime and the proven spelling can never drift."""
    from ..parallel.mesh import MeshPlan
    from ..parallel.trainer import DataParallelTrainer
    from .findings import Finding

    g = PP_GEOMETRY
    tol = float(tolerance_pct) / 100.0
    plan, _, block = _pp_plan_and_program()
    findings = []
    try:
        trainer = DataParallelTrainer(
            block, None, "sgd",
            {"learning_rate": g["lr"], "momentum": g["momentum"]},
            mesh_plan=MeshPlan(data=g["data"], model=g["model"],
                               pipeline=g["pipeline"]))
        rt_report, rt_findings, rt_shard = trainer.mesh_report(
            data_shape=(g["batch"], g["seq_len"]))
    except Exception as e:
        findings.append(Finding(
            "COST001", "pp_transformer_train_step.runtime",
            "the pipelined mesh-tier trainer no longer traces: %s: %s"
            % (type(e).__name__, str(e)[:200])))
        return findings, {}
    findings += rt_findings

    fx = fixture_report.as_dict()
    rt = rt_report.as_dict()
    for metric in ("flops", "transcendentals", "transfer_bytes",
                   "collective_bytes"):
        want, got = float(fx[metric]), float(rt[metric])
        if want and abs(got - want) > tol * want:
            findings.append(Finding(
                "COST001", "pp_transformer_train_step.runtime.%s"
                % metric,
                "the pipelined trainer's REAL step tape models %s = %d "
                "but the budgeted fixture pins %d (tolerance %.0f%%): "
                "the runtime and the proven spelling have drifted "
                "apart" % (metric, int(got), int(want), tol * 100)))
    if rt["peak_hbm_bytes"] > fx["peak_hbm_bytes"] * (1 + tol):
        findings.append(Finding(
            "COST001", "pp_transformer_train_step.runtime.peak_hbm_bytes",
            "the pipelined trainer's REAL step models peak HBM %d, "
            "over the budgeted fixture's %d (tolerance %.0f%%)"
            % (int(rt["peak_hbm_bytes"]), int(fx["peak_hbm_bytes"]),
               tol * 100)))

    fx_axis = fixture_shard.collective_bytes_per_axis
    rt_axis = rt_shard.collective_bytes_per_axis
    for axis in ("pipe", "model"):
        if fx_axis.get(axis, 0) != rt_axis.get(axis, 0):
            findings.append(Finding(
                "COST001",
                "pp_transformer_train_step.runtime.%s_axis_bytes" % axis,
                "runtime %s-axis collective bytes (%d) differ from the "
                "fixture's (%d): the pipelined trainer's step moves "
                "different wire traffic than the proven 1F1B schedule"
                % (axis, rt_axis.get(axis, 0), fx_axis.get(axis, 0))))
    for key in ("pp_hop_bytes", "pp_modeled_bubble_frac"):
        if rt_shard.extras.get(key) != fixture_shard.extras.get(key):
            findings.append(Finding(
                "COST001", "pp_transformer_train_step.runtime.%s" % key,
                "runtime %s (%r) differs from the fixture's (%r): the "
                "trainer no longer runs the pinned schedule geometry"
                % (key, rt_shard.extras.get(key),
                   fixture_shard.extras.get(key))))
    extras = {
        "runtime_peak_hbm_bytes": int(rt["peak_hbm_bytes"]),
        "runtime_collective_bytes": int(rt["collective_bytes"]),
        "runtime_pipe_axis_bytes": int(rt_axis.get("pipe", 0)),
        "runtime_model_axis_bytes": int(rt_axis.get("model", 0)),
    }
    return findings, extras


# the pinned fused-optimizer geometry (docs/fusion.md): parameter
# shapes summing to exactly 32768 f32 elements — a whole number of
# (256, 128) kernel tiles, so the flat space pads by ZERO and the
# declared-vs-modeled byte parity below is EXACT
FUSED_GEOMETRY = {
    "shapes": [(128, 128), (64, 128), (32, 128), (24, 128), (1024,)],
    "lr": 0.1, "momentum": 0.9, "wd": 1e-4,
    "adam_lr": 0.001, "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
}


def _fused_update_programs(kind):
    """(unfused per-param program+avals, seam-honoring flat program+
    avals, flat unfused twin program+avals, optimizer) for one
    optimizer ``kind`` at the pinned geometry."""
    import jax
    import jax.numpy as jnp

    from .. import optimizer as opt_mod
    from ..ops import fused_optimizer as fo
    from ..parallel.functional import functional_optimizer_update

    g = FUSED_GEOMETRY
    if kind == "sgd":
        opt = opt_mod.SGD(learning_rate=g["lr"], momentum=g["momentum"],
                          wd=g["wd"])

        def mk_state(aval):
            return aval
    else:
        opt = opt_mod.Adam(learning_rate=g["adam_lr"], beta1=g["beta1"],
                           beta2=g["beta2"], epsilon=g["epsilon"],
                           wd=g["wd"])

        def mk_state(aval):
            return (aval, aval)

    shapes = [tuple(s) for s in g["shapes"]]
    total = sum(int(_np_prod(s)) for s in shapes)
    param_avals = tuple(jax.ShapeDtypeStruct(s, jnp.float32)
                        for s in shapes)
    flat_aval = jax.ShapeDtypeStruct((total,), jnp.float32)

    def unfused_per_param(ws, gs, states, lr, t):
        new_w, new_s = [], []
        for i, (w, grad, st) in enumerate(zip(ws, gs, states)):
            nw, ns = functional_optimizer_update(opt, i, w, grad, st,
                                                 lr, t)
            new_w.append(nw)
            new_s.append(ns)
        return tuple(new_w), tuple(new_s)

    def fused_flat(w, grad, st, lr, t):
        # the seam: production traces the Pallas kernel; flipping
        # FUSED_OPTIMIZER off degrades to the unfused eqn chain and the
        # FUS001 checks below fail the gate rc=2
        if fo.FUSED_OPTIMIZER:
            return fo.fused_optimizer_update(opt, 0, w, grad, st, lr, t)
        return functional_optimizer_update(opt, 0, w, grad, st, lr, t)

    def unfused_flat(w, grad, st, lr, t):
        return functional_optimizer_update(opt, 0, w, grad, st, lr, t)

    args_pp = (param_avals, param_avals,
               tuple(mk_state(a) for a in param_avals))
    args_flat = (flat_aval, flat_aval, mk_state(flat_aval))
    return (unfused_per_param, args_pp, fused_flat, unfused_flat,
            args_flat, opt, total)


def _np_prod(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def fused_update_fusion_numbers():
    """Deterministic modeled numbers for the fused optimizer update
    (shared by the ``fused_optimizer_update`` budget builder and the
    host ``fusion`` bench stage): per-optimizer unfused/fused bytes,
    bytes-saved, the declared kernel bytes and the chain parity facts."""
    import jax
    import jax.numpy as jnp

    from .cost import _aval_bytes, build_tape
    from .fusion import analyze_tape_fusion

    out = {}
    for kind in ("sgd", "adam"):
        (unfused_pp, args_pp, fused_flat, unfused_flat, args_flat,
         _opt, total) = _fused_update_programs(kind)
        lr_t = (jnp.float32(0.1), jnp.int32(2))

        closed_pp = jax.make_jaxpr(unfused_pp)(*args_pp, *lr_t)
        fr_pp = analyze_tape_fusion(build_tape(closed_pp))

        closed_tw = jax.make_jaxpr(unfused_flat)(*args_flat, *lr_t)
        tape_tw = build_tape(closed_tw)
        fr_tw = analyze_tape_fusion(tape_tw)

        closed_f = jax.make_jaxpr(fused_flat)(*args_flat, *lr_t)
        tape_f = build_tape(closed_f)
        pallas = [op for op in tape_f.ops
                  if op.prim == "pallas_call" and op.params.get("kernel")]
        kernel_bytes = sum(op.bytes_read + op.bytes_written
                           for op in pallas)
        twin_bytes = sum(op.bytes_read + op.bytes_written
                         for op in tape_tw.ops)
        chain = fr_tw.top_chain
        out[kind] = {
            "params": int(total),
            "per_param_chains": len(fr_pp.chains),
            "per_param_bytes_saved": int(fr_pp.total_bytes_saved),
            "unfused_bytes": int(twin_bytes),
            "chain_fused_bytes": int(chain.fused_bytes) if chain else 0,
            "chain_bytes_saved": int(chain.bytes_saved) if chain else 0,
            "saved_pct": round(100.0 * chain.bytes_saved / twin_bytes,
                               2) if (chain and twin_bytes) else 0.0,
            "kernel_present": bool(pallas),
            "kernel_bytes": int(kernel_bytes),
            "unpriced_kernels": list(tape_f.unpriced_kernels),
        }
    out["modeled_fusion_bytes_saved_pct"] = out["sgd"]["saved_pct"]
    return out


def fused_optimizer_update():
    """The fused optimizer update (docs/fusion.md headline) as a static
    proof: the budget row pins the FUSED flat SGD+momentum spelling's
    metrics; the builder runs the FUS001 byte contract for SGD+momentum
    AND Adam — (a) the fused spelling must actually contain the
    declared-cost Pallas kernel (flipping the ``FUSED_OPTIMIZER`` seam
    degrades it to the unfused chain and fails the gate rc=2 naming
    FUS001), (b) the kernel's declared bytes must equal the fusion
    pass's modeled ``fused_bytes`` for the chain it replaces
    (declared-vs-tape parity — EXACT at the pinned zero-padding
    geometry, small slack for the SMEM scalar), and (c) the modeled
    bytes-saved must stay a real win (>= 30% of the unfused chain)."""
    import jax
    import jax.numpy as jnp

    from .cost import analyze_jaxpr, unpriced_findings
    from .findings import Finding

    numbers = fused_update_fusion_numbers()
    findings = []
    for kind in ("sgd", "adam"):
        n = numbers[kind]
        subject = "fused_optimizer_update.%s" % kind
        if not n["kernel_present"]:
            findings.append(Finding(
                "FUS001", subject,
                "the fused optimizer spelling traces NO declared-cost "
                "pallas_call: fusion is disabled (FUSED_OPTIMIZER seam) "
                "or the kernel lost its declare_kernel_cost model — the "
                "fused update would silently run as %d bytes of unfused "
                "eqn chain instead of one %d-byte pass"
                % (n["unfused_bytes"],
                   n["chain_fused_bytes"])))
            continue
        slack = 256          # the SMEM lr scalar + rounding
        if abs(n["kernel_bytes"] - n["chain_fused_bytes"]) > slack:
            findings.append(Finding(
                "FUS001", subject,
                "declared-vs-tape byte parity broken: the kernel "
                "declares %d HBM bytes but one fused pass over the "
                "chain's external buffers moves %d (slack %d) — the "
                "declared cost model and the fusion pass disagree about "
                "what the kernel reads/writes"
                % (n["kernel_bytes"], n["chain_fused_bytes"], slack)))
        if n["chain_bytes_saved"] * 100 < 30 * n["unfused_bytes"]:
            findings.append(Finding(
                "FUS001", subject,
                "the modeled fusion win collapsed: the optimizer chain "
                "saves only %d of %d unfused bytes (< 30%%) — the "
                "unfused spelling got thinner or the chain broke"
                % (n["chain_bytes_saved"], n["unfused_bytes"])))
        if n["unpriced_kernels"]:
            findings.append(Finding(
                "FUS001", subject,
                "the fused spelling contains unpriced pallas_call "
                "kernel(s) %r — they cost zero on the tape"
                % (n["unpriced_kernels"],)))

    # the pinned row: the fused flat SGD+momentum spelling (device-
    # resident, donated in place — transfer is zero by construction)
    (_pp, _args_pp, fused_flat, _tw, args_flat, _opt,
     _total) = _fused_update_programs("sgd")
    closed = jax.make_jaxpr(fused_flat)(*args_flat, jnp.float32(0.1),
                                        jnp.int32(2))
    report = analyze_jaxpr(closed, donated_invars=[0, 1, 2],
                           host_invars=[], fetched_outvars=[])
    findings += unpriced_findings(report,
                                  subject="fused_optimizer_update")
    return report, findings


# the pinned decode_step geometry: the TP_GEOMETRY transformer served
# over a declared model=2 axis — one token step for a fixed batch of
# 4 sequence slots against a 33-page KV pool (1 scratch + 4 full
# sequences), page_size 8.  Small enough to trace in seconds on the
# 1-core CI host but with the whole serving story present: the paged
# gather/scatter, the position<=length mask, and the vocab all-gather
# over `model`
DECODE_GEOMETRY = {
    "vocab_size": 64, "d_model": 32, "n_heads": 4, "n_layers": 2,
    "d_ff": 64, "seq_len": 64,
    "page_size": 8, "slots": 4, "model": 2,
}


def _decode_program(model_axis):
    from ..parallel.mesh import MeshPlan
    from ..transformer import TransformerLMConfig
    from ..transformer.decode import DecodeProgram

    g = DECODE_GEOMETRY
    cfg = TransformerLMConfig(
        vocab_size=g["vocab_size"], d_model=g["d_model"],
        n_heads=g["n_heads"], n_layers=g["n_layers"], d_ff=g["d_ff"],
        seq_len=g["seq_len"])
    return DecodeProgram(cfg, plan=MeshPlan(data=1, model=model_axis),
                         page_size=g["page_size"])


def decode_step():
    """The serving tier's KV-cached token step (docs/serving.md) as a
    static proof: ``DecodeProgram.decode_replica`` — the SAME bound
    method ``DecodeRunner`` jits — traced hardware-free over the
    declared ``model=2`` axis at the pinned ``DECODE_GEOMETRY``.  The
    budget row pins its metrics (a widened cache gather or a vocab
    projection that grew past the all-gather shows up as COST001 with
    no accelerator attached); the builder statically proves the traced
    step WRITES the cache (2 scatters per layer — flipping the
    ``DECODE_WRITE_KV`` seam deletes them and fails the gate rc=2) and
    runs ``decode_runtime_checks``: a real short greedy decode through
    the paged cache against the full-forward reference, so the same
    seam flip also fails as a *numeric* stale-KV divergence."""
    import jax

    from . import shard_prop as sp
    from .cost import analyze_jaxpr, unpriced_findings
    from .findings import Finding

    g = DECODE_GEOMETRY
    prog = _decode_program(g["model"])
    plan = prog.plan
    n_pages = 1 + g["slots"] * prog.pages_per_seq
    avals = prog.decode_avals(n_pages, g["slots"])
    closed = jax.make_jaxpr(prog.decode_replica,
                            axis_env=plan.axis_env())(*avals)

    n = len(prog.program.param_names)
    # flat invars: params, cache_k, cache_v, page_table, lengths, tokens
    host = [n + 2, n + 3, n + 4]
    report = analyze_jaxpr(closed, axis_sizes=plan.axis_sizes(),
                           donated_invars=[n, n + 1],
                           host_invars=host,
                           fetched_outvars=[0])
    findings = unpriced_findings(report, subject="decode_step")

    # the static half of the DECODE_WRITE_KV seam: every layer scatters
    # its new token's K and V into the paged cache — a traced step with
    # fewer than 2 scatters per layer serves stale KV
    scatters = sum(1 for eqn in closed.jaxpr.eqns
                   if "scatter" in eqn.primitive.name)
    want = 2 * prog.cfg.n_layers
    if scatters < want:
        findings.append(Finding(
            "COST001", "decode_step.cache_write",
            "the traced decode step carries %d cache scatter(s), want "
            ">= %d (K and V per layer): the KV write is gone "
            "(DECODE_WRITE_KV seam, or a broken .at[].set spelling) — "
            "every decode step would attend over a cache missing its "
            "own tokens" % (scatters, want)))

    shard = sp.collective_schedule(closed, sp.MeshSpec(plan.axis_sizes()),
                                   subject="decode_step")
    shard.extras.update({
        "decode_geometry": dict(DECODE_GEOMETRY),
        "n_pages": int(n_pages),
        "bytes_per_page": int(prog.bytes_per_page()),
        "pages_per_seq": int(prog.pages_per_seq),
        "cache_scatters": int(scatters),
        "modeled_model_axis_bytes": int(
            shard.collective_bytes_per_axis.get("model", 0)),
    })
    # the RUNTIME half: the real DecodeRunner must reproduce the
    # full-forward reference through the paged cache
    rt_findings, rt_extras = decode_runtime_checks()
    findings += rt_findings
    shard.extras.update(rt_extras)
    return report, findings, shard


def decode_runtime_checks(max_new=6, tolerance=5e-4):
    """Gate the REAL serving decode path: a ``DecodeRunner`` (collapsed
    plan, 1 CPU device) greedy-decodes a short prompt through the paged
    KV cache and must match the no-cache full-forward reference —
    per-step logits within ``tolerance`` and argmax tokens EXACTLY.
    The classic failure this pins down is stale KV (the
    ``DECODE_WRITE_KV`` seam: cache writes skipped, every step attends
    over zeros), which no static metric can see.  Also asserts the
    recompile-free contract: the whole ladder compiles at warmup and
    the decode loop adds zero jit-cache keys."""
    import numpy as _onp

    from ..serving.decode import DecodeRunner
    from .findings import Finding

    findings = []
    try:
        prog = _decode_program(1)
        params = prog.program.init_params(0)
        runner = DecodeRunner(prog, params, slots=2,
                              prefill_buckets=(8, 16), warmup=True)
    except Exception as e:
        findings.append(Finding(
            "COST001", "decode_step.runtime",
            "the serving DecodeRunner no longer builds at the pinned "
            "geometry: %s: %s" % (type(e).__name__, str(e)[:200])))
        return findings, {}

    prompt = (_onp.arange(1, 6, dtype=_onp.int32)
              % prog.cfg.vocab_size)
    with runner._lock:
        pages = runner.pool.alloc(
            runner.pool.pages_for(prompt.size + max_new))
    try:
        row = _onp.zeros(runner.pages_per_seq, _onp.int32)
        row[:len(pages)] = pages
        seq = list(prompt)
        pt = _onp.zeros((runner.slots, runner.pages_per_seq),
                        _onp.int32)
        lengths = _onp.zeros(runner.slots, _onp.int32)
        toks = _onp.zeros(runner.slots, _onp.int32)
        pt[0] = row
        max_diff, mismatch_at = 0.0, None
        cached_logits = runner.prefill(prompt, pages)
        for step in range(max_new):
            # full-forward oracle over the sequence so far (scratch
            # pages only — never touches the live allocation)
            ref_logits = runner.prefill(
                _onp.asarray(seq, _onp.int32), _onp.zeros(0, _onp.int32))
            diff = float(_onp.max(_onp.abs(cached_logits - ref_logits)))
            max_diff = max(max_diff, diff)
            if (mismatch_at is None
                    and (diff > tolerance
                         or int(cached_logits.argmax())
                         != int(ref_logits.argmax()))):
                mismatch_at = step
            nxt = int(ref_logits.argmax())
            seq.append(nxt)
            lengths[0] = len(seq) - 1
            toks[0] = nxt
            cached_logits = runner.decode_step(pt, lengths, toks)[0]
        if mismatch_at is not None:
            findings.append(Finding(
                "COST001", "decode_step.runtime.numerics",
                "cached decode diverged from the full-forward reference "
                "at generated token %d (max |logit| diff %.3e, tolerance "
                "%.0e): the paged KV cache does not reproduce the model "
                "— stale KV (the DECODE_WRITE_KV seam), a wrong page "
                "mapping, or a broken position mask"
                % (mismatch_at, max_diff, tolerance)))
        recompiles = runner.recompiles_since_warmup()
        if recompiles:
            findings.append(Finding(
                "COST001", "decode_step.runtime.recompiles",
                "the decode loop added %d jit-cache key(s) after warmup "
                "— the prefill bucket ladder or the fixed slot batch "
                "leaked a new trace signature; steady-state serving "
                "would recompile per request" % recompiles))
        extras = {
            "runtime_max_logit_diff": max_diff,
            "runtime_tokens_checked": int(max_new),
            "runtime_recompiles": int(recompiles),
            "runtime_admission_hbm_bytes": int(
                runner.admission_hbm_bytes()),
        }
        return findings, extras
    finally:
        with runner._lock:
            runner.pool.free(pages)


def codegen_generated_kernels():
    """The mxgen generated kernels (docs/fusion.md "Generated kernels")
    as a static proof: build the shipped top-N chains of the transformer
    train-step and ZeRO-1 tapes into registered Pallas kernels, then
    gate three invariants through FUS001 — (a) every registered kernel's
    emitted body must reproduce its tape reference bit-for-exact on the
    host path (flipping the ``MXGEN_LOWER_EXACT`` seam mislowers one
    eqn and fails the gate rc=2 naming FUS001), (b) every kernel must
    keep its auto-declared ``KERNEL_COSTS`` entry and the declared
    bytes must equal the chain's modeled per-call fused bytes (parity
    is an identity at registration — a drift means the registration
    path changed), and (c) the traced all-kernels program must price
    every pallas_call (no unpriced generated kernel).  Unlowerable
    shipped chains surface their GEN001s here too, so the budget gate
    and ``--self-check`` agree.  The budget row pins the metrics of one
    pass over every generated kernel (``generated_call`` per kernel,
    whole-array refs)."""
    import jax

    from ..ops import generated_kernels as gen
    from . import codegen as cg
    from .cost import KERNEL_COSTS, analyze_jaxpr, unpriced_findings
    from .findings import Finding

    findings = []
    kernels = gen.build_shipped_generated()
    lowered = {lk.name: lk for lk in cg.shipped_lowered()}
    for lk in lowered.values():
        findings += list(lk.findings)       # GEN001: unlowerable chains

    for gk in kernels:
        subject = "codegen_generated_kernels.%s" % gk.name
        if not gk.equivalence_ok:
            findings.append(Finding(
                "FUS001", subject,
                "generated kernel diverges from its tape reference "
                "(max err %s, tolerance %.0e): the emitted body "
                "mislowers at least one eqn (the MXGEN_LOWER_EXACT "
                "seam, or a broken _emit_rhs rule) — the auto-declared "
                "cost prices a kernel that does not compute the chain"
                % (gk.equivalence_err, cg.EQUIV_TOL)))
        cost_fn = KERNEL_COSTS.get(gk.name)
        if cost_fn is None:
            findings.append(Finding(
                "FUS001", subject,
                "generated kernel lost its auto-declared KERNEL_COSTS "
                "entry — it would trace as an unpriced pallas_call and "
                "cost zero on every tape (COST006 names the registry "
                "side; this is the gate side)"))
            continue
        c = cost_fn(None)
        declared = int(c["bytes_read"]) + int(c["bytes_written"])
        lk = lowered.get(gk.name)
        per_call = (int(lk.fused_bytes) // max(int(lk.scale), 1)
                    if lk is not None else declared)
        if declared != per_call:
            findings.append(Finding(
                "FUS001", subject,
                "declared-vs-tape byte parity broken: the auto-declared "
                "cost moves %d HBM bytes but one fused pass over the "
                "chain's external buffers moves %d — parity is an "
                "identity by construction (register_generated copies "
                "the chain's split verbatim); the registration path "
                "changed" % (declared, per_call)))

    # the pinned row: one generated_call per registered kernel, traced
    # hardware-free — every pallas_call prices through its auto-declared
    # cost entry, so the row IS the sum of the declared contracts
    sizes = [len(gk.in_avals) for gk in kernels]
    specs = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
             for gk in kernels for a in gk.in_avals]

    def _all_generated(*flat):
        outs, i = [], 0
        for gk, n in zip(kernels, sizes):
            outs += gen.generated_call(gk, *flat[i:i + n],
                                       interpret=True)
            i += n
        return tuple(outs)

    closed = jax.make_jaxpr(_all_generated)(*specs)
    report = analyze_jaxpr(closed)
    findings += unpriced_findings(report,
                                  subject="codegen_generated_kernels")
    return report, findings


BUDGET_MODELS = {
    "mlp_train_step": mlp_train_step,
    "mlp_infer": mlp_infer,
    "convnet_infer": convnet_infer,
    "resnet50_train_step": resnet50_train_step,
    "zero1_mlp_train_step": zero1_mlp_train_step,
    "bf16_zero1_train_step": bf16_zero1_train_step,
    "ring_attention_fwd": ring_attention_fwd,
    "ulysses_attention": ulysses_attention,
    "tp_transformer_train_step": tp_transformer_train_step,
    "pp_transformer_train_step": pp_transformer_train_step,
    "fused_optimizer_update": fused_optimizer_update,
    "decode_step": decode_step,
    "codegen_generated_kernels": codegen_generated_kernels,
}


def build_fusion_report(name):
    """mxfuse FusionReport for one budget model's UNFUSED program (the
    chains a fused kernel could still claim), or None for models whose
    spelling the fusion CLI does not analyze.  ``--cost --fusion``."""
    import jax
    import jax.numpy as jnp

    from .fusion import fusion_from_fn, fusion_from_jaxpr

    if name == "fused_optimizer_update":
        unfused_pp, args_pp, *_rest = _fused_update_programs("sgd")
        return fusion_from_fn(unfused_pp, *args_pp, jnp.float32(0.1),
                              jnp.int32(2))
    if name == "mlp_train_step":
        from ..gluon import loss as gloss
        from ..parallel.trainer import DataParallelTrainer
        trainer = DataParallelTrainer(
            _mlp_block(), gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9}, mesh=_cpu_mesh())
        return trainer.fusion_report(data_shape=(64, 16),
                                     label_shape=(64,))
    if name == "zero1_mlp_train_step":
        from . import shard_fixtures as sf
        k = DECLARED_AXIS
        step, args = sf.zero1_step_program(k)
        closed = jax.make_jaxpr(step, axis_env=[("data", k)])(*args)
        return fusion_from_jaxpr(closed, axis_sizes={"data": k})
    if name == "tp_transformer_train_step":
        # the same trace spelling mxgen lowers (codegen.shipped_tape) —
        # what --fusion ranks here is exactly what the generated
        # kernels replace
        from .codegen import shipped_tape
        from .fusion import analyze_tape_fusion
        return analyze_tape_fusion(shipped_tape("tp_transformer"))
    return None


def build_model(name):
    """(CostReport, [Finding], ShardReport-or-None) for one registered
    budget model.  Only the shard-aware models (the ZeRO-1 step, ring
    attention) produce a ShardReport; the pre-mxshard builders return
    their original 2-tuple and are normalized here."""
    if name not in BUDGET_MODELS:
        raise KeyError("unknown budget model %r (have: %s)"
                       % (name, ", ".join(sorted(BUDGET_MODELS))))
    out = BUDGET_MODELS[name]()
    if len(out) == 2:
        report, findings = out
        return report, findings, None
    return out


def compute_budgets(models=None):
    """{model: {metric: value}} for the given (default: all non-heavy)
    budget models — what ``tools/update_budgets.py`` writes."""
    out = {}
    for name in sorted(models if models is not None
                       else [m for m in BUDGET_MODELS
                             if m != "resnet50_train_step"]):
        report, _, _ = build_model(name)
        d = report.as_dict()
        out[name] = {m: int(d[m]) for m in BUDGET_METRICS}
    return out


def check_budgets(budget_path, tolerance_pct=None):
    """Gate the budget file: rebuild every budgeted model, compare each
    pinned metric within tolerance, and fold in the models' own DST /
    shard findings.  Returns (findings, {model: CostReport},
    {model: ShardReport})."""
    import json

    from .findings import Finding

    with open(budget_path) as f:
        budget = json.load(f)
    tol = float(tolerance_pct if tolerance_pct is not None
                else budget.get("tolerance_pct", 10)) / 100.0
    findings, reports, shards = [], {}, {}
    budgeted = budget.get("models", {})
    for name in sorted(budgeted):
        row = budgeted[name]
        if name not in BUDGET_MODELS:
            findings.append(Finding(
                "COST001", name,
                "STATIC_BUDGETS.json pins %r but no such budget model "
                "is registered — the gate is checking nothing; remove "
                "the row or restore the model" % (name,)))
            continue
        try:
            report, dst, shard = build_model(name)
        except Exception as e:
            findings.append(Finding(
                "COST001", name,
                "budget model %r no longer builds: %s: %s"
                % (name, type(e).__name__, str(e)[:200])))
            continue
        reports[name] = report
        if shard is not None:
            shards[name] = shard
        findings += dst
        d = report.as_dict()
        for metric in BUDGET_METRICS:
            if metric not in row:
                continue
            want, got = float(row[metric]), float(d[metric])
            if want == 0 and got == 0:
                continue
            hi = want * (1 + tol)
            lo = want * (1 - tol)
            if got > hi:
                findings.append(Finding(
                    "COST001", "%s.%s" % (name, metric),
                    "modeled %s of %s is %d, %.1f%% over the budget %d "
                    "(tolerance %.0f%%) — a regression, or regenerate "
                    "via tools/update_budgets.py if intentional"
                    % (metric, name, int(got),
                       (got / want - 1) * 100 if want else 0.0,
                       int(want), tol * 100)))
            elif got < lo:
                findings.append(Finding(
                    "COST002", "%s.%s" % (name, metric),
                    "modeled %s of %s is %d, %.1f%% under the budget %d "
                    "— bank the improvement: tools/update_budgets.py"
                    % (metric, name, int(got),
                       (1 - got / want) * 100 if want else 0.0,
                       int(want))))
    for name in sorted(set(BUDGET_MODELS) - set(budgeted)
                       - {"resnet50_train_step"}):
        findings.append(Finding(
            "COST002", name,
            "budget model %r has no STATIC_BUDGETS.json row — it is "
            "not gated; add it via tools/update_budgets.py" % (name,)))
    findings += _check_codegen_chains(budget, tol)
    return findings, reports, shards


def _check_codegen_chains(budget, tol):
    """Gate the ``codegen_chains`` section (schema 4): each pinned
    per-chain bytes-saved must match the live mxgen lowering within
    tolerance, every pinned chain must still ship, and every shipped
    chain must be pinned — a mislowered/reordered chain fails COST001
    here even before its kernel's FUS001 equivalence does."""
    from .findings import Finding

    pinned = budget.get("codegen_chains")
    if pinned is None:
        return []
    findings = []
    try:
        from .codegen import shipped_chain_rows
        live = shipped_chain_rows()
    except Exception as e:
        return [Finding(
            "COST001", "codegen_chains",
            "the mxgen shipped-chain lowering no longer builds: %s: %s"
            % (type(e).__name__, str(e)[:200]))]
    for name in sorted(pinned):
        if name not in live:
            findings.append(Finding(
                "COST001", "codegen_chains.%s" % name,
                "STATIC_BUDGETS.json pins generated chain %r but mxgen "
                "no longer ships it — the tape's chain ranking moved or "
                "the chain stopped lowering; regenerate via "
                "tools/update_budgets.py if intentional" % (name,)))
            continue
        want, got = float(pinned[name]), float(live[name])
        if want <= 0 or abs(got - want) > tol * want:
            findings.append(Finding(
                "COST001", "codegen_chains.%s" % name,
                "modeled bytes-saved of generated chain %s is %d vs the "
                "pinned %d (tolerance %.0f%%) — the chain mined from "
                "the tape changed shape; a mislowering or an unfused-"
                "spelling drift" % (name, int(got), int(want),
                                    tol * 100)))
    for name in sorted(set(live) - set(pinned)):
        findings.append(Finding(
            "COST002", "codegen_chains.%s" % name,
            "mxgen ships generated chain %r with no codegen_chains "
            "row — it is not gated; add it via tools/update_budgets.py"
            % (name,)))
    return findings
