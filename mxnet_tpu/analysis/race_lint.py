"""Concurrency lint ("mxrace"): AST analysis of the threaded host tiers.

The reference stack's core is a threaded dependency engine
(``src/engine/threaded_engine.h``): push threads, worker pools and the
engine share mutable state behind mutexes.  Our TPU rebuild keeps that
concurrency in its *host* tiers — the PS server's serve threads, the
heartbeat watchdog, the serving batcher/fleet, the pipeline supervisor
— and hand review has already caught real shipped races there (PR 6:
the unlocked ``_key_owner`` iteration inside the watchdog callback).
mxrace turns that bug class into a hardware-free static gate, in the
mxlint house style: parse, infer, emit Findings.

Five rules:

- **RACE001 lock-guard inference** — per class, every ``self.X``
  access is classified by the set of ``with self._lock:`` regions held
  at that point.  An attribute *written* under a lock anywhere is
  inferred guarded by it; any access holding none of the guard set
  (outside ``__init__``) is a race candidate — the exact
  ``_key_owner`` class of bug.
- **RACE002 lock-order** — ``with B:`` inside ``with A:`` is an
  acquired-while-holding edge ``A -> B``.  Cycles across the swept
  modules are potential deadlocks; ``docs/concurrency.md`` pins the
  sanctioned acquisition order and the sweep checks the table both
  ways (an observed edge missing from the table, or a pinned row no
  longer observed, fails — the DOC001/TEL001 sync pattern).
- **RACE003 blocking-under-lock** — socket/RPC I/O, unbounded
  ``queue.get``/``join``, ``sleep``, subprocess calls and
  ``chaos.maybe_inject`` sites (which can delay or raise by design)
  inside a held region serialize every sibling of that lock behind
  I/O — and turn a chaos delay into a server-wide stall.
- **RACE004 thread lifecycle** — a ``Thread(...)`` started with
  neither ``daemon=True`` nor a join/shutdown path outlives shutdown
  and hangs interpreter exit.
- **RACE005 callback-under-lock** — invoking a user/foreign callback
  while holding the owner's lock (the PR-6 watchdog class): the
  callback can call back in (deadlock) or block the owner for an
  unbounded time.

The analysis is intra-class with one interprocedural refinement:
private helpers (``self._foo()``) inherit the lock set their callers
*always* hold (the ``*_locked`` helper convention), computed to a
fixpoint.  The rules are heuristic (python is dynamic); deliberate
exceptions carry a trailing ``# mxlint: disable=RACEnnn`` with a
justification comment — policy in docs/concurrency.md.
"""
from __future__ import annotations

import ast
import os
import re

from .findings import Finding, filter_findings

__all__ = ["lint_race_source", "lint_race_file", "lint_threaded_sources",
           "lock_order_findings", "parse_hierarchy", "race_summary",
           "threaded_targets"]

# threading.X() / X() calls that create a mutual-exclusion region
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
# thread-safe (or thread-handle) objects: excluded from guard inference
_SAFE_FACTORIES = {"Event", "Queue", "SimpleQueue", "LifoQueue",
                   "PriorityQueue", "Semaphore", "BoundedSemaphore",
                   "Barrier", "Thread", "local"}
# method calls that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "add", "remove", "discard",
             "pop", "popitem", "clear", "update", "setdefault",
             "appendleft", "popleft", "sort", "reverse"}
# attribute calls that block on I/O regardless of arguments
_BLOCKING_IO = {"recv", "recv_into", "recvfrom", "accept", "connect",
                "sendall", "makefile", "communicate", "check_output",
                "check_call"}
# attribute calls that block only in their zero-positional-arg /
# unbounded spelling (queue.get(), thread.join(); dict.get(k) and
# " ".join(xs) take positionals)
_BLOCKING_NOARG = {"get", "join", "put"}
# names that look like user-provided callbacks when called
_CALLBACK_NAME = re.compile(
    r"(callback|_cb$|^cb$|cbs$|hook|listener|handler|^on_[a-z_]+$)")


def _is_factory(node, names):
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
    return name in names


class _Access:
    __slots__ = ("attr", "kind", "method", "lineno", "held")

    def __init__(self, attr, kind, method, lineno, held):
        self.attr, self.kind = attr, kind
        self.method, self.lineno = method, lineno
        self.held = frozenset(held)


class _Owner:
    """One lock scope: a class (``self.X`` locks) or the module itself
    (module-global locks used by module-level functions)."""

    def __init__(self, name, is_module=False):
        self.name = name
        self.is_module = is_module
        self.locks = set()         # lock attrs/globals
        self.lock_dicts = set()    # dicts filled with per-key locks
        self.lock_methods = set()  # methods returning a lock
        self.safe = set()          # Event/Queue/Thread attrs (skip RACE001)
        self.methods = {}          # name -> FunctionDef (class mode)
        self.foreign = set()       # attrs assigned straight from a parameter
        self.globals = set()       # module-level names (module mode)
        self.accesses = []         # _Access records (final pass)
        self.call_sites = {}       # method -> [frozenset(held), ...]
        self.entry = {}            # method -> frozenset(held at entry)
        self.callers = {}          # method -> {caller qualnames}
        self.init_only = set()     # private methods reachable only
        #                            from __init__ (pre-thread setup)

    def prefix(self, lock):
        return "%s.%s" % (self.name, lock)

    def attr_of(self, node, shadow=()):
        """The tracked name a node refers to: ``self.X`` in class mode,
        an unshadowed module global in module mode."""
        if self.is_module:
            if isinstance(node, ast.Name) and node.id not in shadow and \
                    (node.id in self.locks or node.id in self.globals):
                return node.id
            return None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return None

    def lock_of(self, node, aliases, shadow=()):
        """Canonical (unprefixed) lock name for an acquisition
        expression, or None: ``self._lock`` / ``self._locks[k]`` /
        ``self._key_lock(k)`` / a local alias of one."""
        a = self.attr_of(node, shadow)
        if a is not None:
            return a if a in self.locks else None
        if isinstance(node, ast.Call):
            fa = self.attr_of(node.func, shadow)
            if fa is not None and fa in self.lock_methods:
                return fa + "()"
        if isinstance(node, ast.Subscript):
            va = self.attr_of(node.value, shadow)
            if va is not None and va in self.lock_dicts:
                return va + "[]"
        if isinstance(node, ast.Name):
            al = aliases.get(node.id)
            if al is not None and al[0] == "lock":
                return al[1]
        return None


def _root_attr(owner, node, shadow=()):
    """Innermost tracked attr under subscript/attribute chains:
    ``self.X[k]`` -> X, ``self.X.y[k]`` -> X, alias-free."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        a = owner.attr_of(node, shadow)
        if a is not None:
            return a
        node = node.value
    return owner.attr_of(node, shadow)


def _inferred_guard(owner, accs):
    """-> (guard lock set, runtime locked writes) for one attribute's
    accesses.  ``__init__`` and init-only setup methods are excluded
    from inference: their writes run before any sibling thread exists,
    so they neither establish nor violate a guard."""
    locked_writes = [a for a in accs
                     if a.kind == "w" and a.held
                     and a.method != "__init__"
                     and a.method not in owner.init_only]
    if not locked_writes:
        return set(), []
    guard = set(locked_writes[0].held)
    for a in locked_writes[1:]:
        guard &= a.held
    return guard, locked_writes


class _ThreadSite:
    __slots__ = ("lineno", "daemon", "binding", "func")

    def __init__(self, lineno, daemon, binding, func):
        self.lineno, self.daemon = lineno, daemon
        self.binding, self.func = binding, func


class _Analyzer:
    """Whole-module analysis: builds owners, runs the entry-lock
    fixpoint, then a collecting walk that records accesses, edges and
    direct findings."""

    def __init__(self, tree, filename, suppressed):
        self.tree = tree
        self.filename = filename
        self.suppressed = suppressed
        self.findings = []
        self.edges = []            # (outer, inner, "file:line") prefixed
        self.thread_sites = []
        self.joined = set()        # ("attr"/"name", name) / ("func", qual)
        self.daemon_set = set()    # same keys as joined
        self._seen_threads = set()
        self._emitted = set()
        self.owners = []

    # -- collection -------------------------------------------------------
    def build(self):
        mod = _Owner(os.path.splitext(os.path.basename(self.filename))[0],
                     is_module=True)
        for st in self.tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name):
                name = st.targets[0].id
                if _is_factory(st.value, _LOCK_FACTORIES):
                    mod.locks.add(name)
                elif name != "__all__":
                    mod.globals.add(name)
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.methods[st.name] = st
        self.owners.append(mod)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self.owners.append(self._build_class(node))

    def _build_class(self, cls):
        owner = _Owner(cls.name)
        for st in cls.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owner.methods[st.name] = st
        for fn in owner.methods.values():
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                      if a.arg != "self"}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    val = node.value
                    for t in node.targets:
                        a = owner.attr_of(t)
                        if a is None:
                            # self.X[k] = threading.Lock(): per-key dict
                            if isinstance(t, ast.Subscript):
                                va = owner.attr_of(t.value)
                                if va and _is_factory(val, _LOCK_FACTORIES):
                                    owner.lock_dicts.add(va)
                            continue
                        if _is_factory(val, _LOCK_FACTORIES):
                            owner.locks.add(a)
                        elif _is_factory(val, _SAFE_FACTORIES):
                            owner.safe.add(a)
                        elif isinstance(val, ast.Name) and val.id in params:
                            owner.foreign.add(a)
                elif isinstance(node, ast.Call):
                    f = node.func
                    # self.X.setdefault(k, threading.Lock())
                    if isinstance(f, ast.Attribute) and \
                            f.attr == "setdefault" and len(node.args) > 1 \
                            and _is_factory(node.args[1], _LOCK_FACTORIES):
                        va = owner.attr_of(f.value)
                        if va:
                            owner.lock_dicts.add(va)
        for name, fn in owner.methods.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    if any(_is_factory(sub, _LOCK_FACTORIES)
                           for sub in ast.walk(node.value)):
                        owner.lock_methods.add(name)
                        break
        return owner

    # -- walking ----------------------------------------------------------
    def run(self):
        self.build()
        # fixpoint: private helpers inherit the intersection of their
        # observed callers' held sets (the *_locked convention)
        for _ in range(4):
            for owner in self.owners:
                owner.call_sites = {}
            self._walk_all(collect=False)
            changed = False
            for owner in self.owners:
                new = {}
                for m in owner.methods:
                    if not m.startswith("_") or m.startswith("__"):
                        continue
                    sites = owner.call_sites.get(m)
                    if sites:
                        new[m] = frozenset.intersection(*sites)
                if new != owner.entry:
                    owner.entry = new
                    changed = True
            if not changed:
                break
        for owner in self.owners:
            owner.call_sites = {}
            owner.callers = {}
            owner.accesses = []
        self._walk_all(collect=True)
        for owner in self.owners:
            owner.init_only = self._init_only(owner)
            self._guard_findings(owner)
        self._thread_findings()

    @staticmethod
    def _init_only(owner):
        """Private methods whose every observed caller is ``__init__``
        or another init-only method: pre-thread setup (e.g. a WAL
        ``_recover`` that runs before the socket binds) shares the
        ``__init__`` exemption.  Closure quals (``x.<locals>.y``) never
        qualify as callers — a closure defined in ``__init__`` may be a
        thread target that runs much later."""
        out = set()
        changed = True
        while changed:
            changed = False
            for m in owner.methods:
                if m in out or not m.startswith("_") or m.startswith("__"):
                    continue
                callers = owner.callers.get(m)
                if callers and all(c == "__init__" or c in out
                                   for c in callers):
                    out.add(m)
                    changed = True
        return out

    def _walk_all(self, collect):
        for owner in self.owners:
            for name, fn in sorted(owner.methods.items()):
                entry = sorted(owner.entry.get(name, ()))
                _Walker(self, owner, name, entry, collect, fn).walk()

    # -- emission ---------------------------------------------------------
    def emit(self, rule, lineno, msg):
        if rule in self.suppressed.get(lineno, ()):
            return
        key = (rule, lineno, msg)
        if key in self._emitted:
            return
        self._emitted.add(key)
        self.findings.append(
            Finding(rule, "%s:%d" % (self.filename, lineno), msg))

    def add_edge(self, outer, inner, lineno):
        if "RACE002" in self.suppressed.get(lineno, ()):
            return
        self.edges.append((outer, inner,
                           "%s:%d" % (self.filename, lineno)))

    # -- RACE001 ----------------------------------------------------------
    def _guard_findings(self, owner):
        by_attr = {}
        for acc in owner.accesses:
            by_attr.setdefault(acc.attr, []).append(acc)
        for attr in sorted(by_attr):
            accs = by_attr[attr]
            guard, locked_writes = _inferred_guard(owner, accs)
            if not locked_writes:
                continue
            rep = min(locked_writes, key=lambda a: (a.lineno, a.method))
            if not guard:
                self.emit("RACE001", rep.lineno,
                          "attribute '%s' of %s is written under "
                          "inconsistent lock sets across methods — no "
                          "single lock guards it; pick one lock and hold "
                          "it at every mutation" % (attr, owner.name))
                continue
            pretty = " or ".join(sorted(owner.prefix(g) for g in guard))
            seen = set()
            for a in sorted(accs, key=lambda a: (a.lineno, a.kind)):
                if a.method == "__init__" or a.method in owner.init_only \
                        or (a.held & guard):
                    continue
                if a.lineno in seen:
                    continue
                seen.add(a.lineno)
                self.emit("RACE001", a.lineno,
                          "attribute '%s' of %s is %s here without %s, "
                          "but %s() mutates it under that lock (line %d) "
                          "— a concurrent mutation can corrupt or resize "
                          "it mid-access (the PR-6 _key_owner bug class)"
                          % (attr, owner.name,
                             "written" if a.kind == "w" else "read",
                             pretty, rep.method, rep.lineno))

    # -- RACE004 ----------------------------------------------------------
    def note_thread(self, call, binding, func):
        if id(call) in self._seen_threads:
            return
        self._seen_threads.add(id(call))
        daemon = any(k.arg == "daemon" and
                     isinstance(k.value, ast.Constant) and
                     k.value.value is True for k in call.keywords)
        self.thread_sites.append(
            _ThreadSite(call.lineno, daemon, binding, func))

    def _thread_findings(self):
        for site in sorted(self.thread_sites, key=lambda s: s.lineno):
            if site.daemon:
                continue
            if site.binding and (site.binding in self.joined or
                                 site.binding in self.daemon_set):
                continue
            if ("func", site.func) in self.joined:
                continue
            self.emit("RACE004", site.lineno,
                      "Thread started with neither daemon=True nor a "
                      "join/shutdown path (no .join() or .daemon=True "
                      "found for it) — a non-daemon thread with no "
                      "registered join outlives shutdown and hangs "
                      "interpreter exit")


class _Walker:
    """Statement walker for one method/function: tracks the held-lock
    stack through ``with`` regions and explicit acquire/release."""

    def __init__(self, an, owner, qual, entry_held, collect, fn,
                 shadow=None):
        self.an, self.owner, self.qual = an, owner, qual
        self.held = list(entry_held)
        self.collect = collect
        self.fn = fn
        self.aliases = {}     # local name -> ("lock", l)|("attr", a)|("cb",)
        if shadow is not None:
            self.shadow = shadow
        elif owner.is_module:
            self.shadow = self._shadowed(fn)
        else:
            self.shadow = frozenset()

    @staticmethod
    def _shadowed(fn):
        """Names local to fn (params + assigned without ``global``)."""
        hidden, globs = set(), set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globs.update(node.names)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                hidden.add(node.id)
        for a in fn.args.args + fn.args.kwonlyargs:
            hidden.add(a.arg)
        return frozenset(hidden - globs)

    def walk(self):
        self.block(self.fn.body)

    # -- statements -------------------------------------------------------
    def block(self, stmts):
        for st in stmts:
            self.stmt(st)

    def stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a closure runs later (often as a thread target): fresh
            # held set, accesses still belong to this owner
            _Walker(self.an, self.owner,
                    "%s.<locals>.%s" % (self.qual, st.name), (),
                    self.collect, st, shadow=self.shadow).walk()
        elif isinstance(st, ast.ClassDef):
            pass   # nested classes get their own owner via build()
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            self._with(st)
        elif isinstance(st, ast.Assign):
            self._assign(st)
        elif isinstance(st, ast.AugAssign):
            self._write_target(st.target, also_read=True)
            self.expr(st.value)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._write_target(st.target)
                self.expr(st.value)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._write_target(t)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._for(st)
        else:
            for _field, value in ast.iter_fields(st):
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        self.block(value)
                    else:
                        for v in value:
                            if isinstance(v, ast.expr):
                                self.expr(v)
                            elif isinstance(v, ast.stmt):
                                self.stmt(v)
                            elif isinstance(v, (ast.excepthandler,)):
                                self.block(v.body)
                elif isinstance(value, ast.expr):
                    self.expr(value)

    def _with(self, st):
        acquired = []
        for item in st.items:
            ln = self.owner.lock_of(item.context_expr, self.aliases,
                                    self.shadow)
            if ln is not None:
                # record the helper call site before entering the region
                if isinstance(item.context_expr, ast.Call) and \
                        ln.endswith("()"):
                    self.owner.call_sites.setdefault(ln[:-2], []).append(
                        frozenset(self.held))
                    self.owner.callers.setdefault(
                        ln[:-2], set()).add(self.qual)
                    for a in item.context_expr.args:
                        self.expr(a)
                if self._acquire(ln, item.context_expr.lineno):
                    acquired.append(ln)
            else:
                self.expr(item.context_expr)
            if item.optional_vars is not None:
                self._write_target(item.optional_vars)
        self.block(st.body)
        for ln in reversed(acquired):
            self.held.remove(ln)

    def _acquire(self, ln, lineno):
        if self.held and ln not in self.held and self.collect:
            self.an.add_edge(self.owner.prefix(self.held[-1]),
                             self.owner.prefix(ln), lineno)
        if ln not in self.held:
            self.held.append(ln)
            return True
        return False

    def _assign(self, st):
        val = st.value
        if len(st.targets) == 1 and isinstance(st.targets[0], ast.Name):
            tname = st.targets[0].id
            ln = self.owner.lock_of(val, self.aliases, self.shadow)
            a = self.owner.attr_of(val, self.shadow)
            if ln is not None:
                self.aliases[tname] = ("lock", ln)
            elif a is not None:
                self.aliases[tname] = ("attr", a)
            else:
                self.aliases.pop(tname, None)
        # thread creation bindings: self.X = Thread(...) / t = Thread(...)
        # / threads = [Thread(...) for ...]
        tcalls = [n for n in ast.walk(val)
                  if isinstance(n, ast.Call) and _is_factory(n, {"Thread"})]
        if tcalls and st.targets:
            binding = self._binding_key(st.targets[0])
            for c in tcalls:
                self.an.note_thread(c, binding, self.qual)
        # t.daemon = True / self.X.daemon = True
        for t in st.targets:
            if isinstance(t, ast.Attribute) and t.attr == "daemon" and \
                    isinstance(val, ast.Constant) and val.value is True:
                key = self._binding_key(t.value)
                if key:
                    self.an.daemon_set.add(key)
        for t in st.targets:
            self._write_target(t)
        self.expr(val)

    def _binding_key(self, node):
        a = None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            a = ("attr", node.attr)
        elif isinstance(node, ast.Name):
            a = ("name", node.id)
        return a

    def _for(self, st):
        self.expr(st.iter)
        # `for cb in self._callbacks:` — mark the loop var callback-ish
        it_attr = _root_attr(self.owner, st.iter, self.shadow)
        if it_attr and _CALLBACK_NAME.search(it_attr):
            for n in ast.walk(st.target):
                if isinstance(n, ast.Name):
                    self.aliases[n.id] = ("cb",)
        else:
            self._write_target(st.target, record=False)
        self.block(st.body)
        self.block(st.orelse)

    def _write_target(self, t, also_read=False, record=True):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._write_target(t=e, also_read=also_read, record=record)
            return
        if isinstance(t, ast.Starred):
            self._write_target(t.value, also_read=also_read, record=record)
            return
        if not record:
            return
        attr = None
        a = self.owner.attr_of(t, self.shadow)
        if a is not None:
            attr = a
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            attr = _root_attr(self.owner, t, self.shadow)
            if attr is None and isinstance(t.value, ast.Name):
                al = self.aliases.get(t.value.id)
                if al is not None and al[0] == "attr":
                    attr = al[1]   # e = self._entries[k]; e.field = v
            # the subscript/index expressions are reads
            for _field, value in ast.iter_fields(t):
                if isinstance(value, ast.expr) and value is not t.value:
                    self.expr(value)
            if isinstance(t.value, (ast.Subscript, ast.Attribute)):
                self.expr(t.value)
        if attr is not None:
            self._record(attr, "w", t.lineno)
            if also_read:
                self._record(attr, "r", t.lineno)

    # -- expressions ------------------------------------------------------
    def _record(self, attr, kind, lineno):
        if not self.collect:
            return
        o = self.owner
        if attr in o.locks or attr in o.lock_dicts or attr in o.safe or \
                attr in o.methods or attr in o.lock_methods:
            return
        o.accesses.append(_Access(attr, kind, self.qual, lineno, self.held))

    def expr(self, e):
        if e is None:
            return
        if isinstance(e, ast.Call):
            self._call(e)
            return
        if isinstance(e, ast.Lambda):
            # lambdas in this codebase are synchronous predicates/keys
            # (cv.wait_for re-acquires before evaluating; sort keys run
            # inline) — unlike def closures (thread targets), they
            # inherit the current held set
            w = _Walker(self.an, self.owner,
                        "%s.<locals>.<lambda>" % self.qual,
                        tuple(self.held), self.collect, e,
                        shadow=self.shadow)
            w.expr(e.body)
            return
        a = self.owner.attr_of(e, self.shadow)
        if a is not None:
            if isinstance(getattr(e, "ctx", None), (ast.Store, ast.Del)):
                self._record(a, "w", e.lineno)
            else:
                self._record(a, "r", e.lineno)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, (ast.expr, ast.comprehension)):
                if isinstance(child, ast.comprehension):
                    self.expr(child.iter)
                    for c in child.ifs:
                        self.expr(c)
                else:
                    self.expr(child)

    def _call(self, e):
        f = e.func
        fattr = f.attr if isinstance(f, ast.Attribute) else None
        fname = f.id if isinstance(f, ast.Name) else None
        recv_attr = self.owner.attr_of(f.value, self.shadow) \
            if isinstance(f, ast.Attribute) else None

        # mutator: self.X.append(...) / self.X[k].update(...)
        if fattr in _MUTATORS and isinstance(f, ast.Attribute):
            root = _root_attr(self.owner, f.value, self.shadow)
            if root is None and isinstance(f.value, ast.Name):
                al = self.aliases.get(f.value.id)
                if al is not None and al[0] == "attr":
                    root = al[1]
            if root is not None:
                self._record(root, "w", e.lineno)

        # explicit acquire/release on a known lock
        if fattr == "acquire":
            ln = self.owner.lock_of(f.value, self.aliases, self.shadow)
            if ln is not None:
                self._acquire(ln, e.lineno)
        elif fattr == "release":
            ln = self.owner.lock_of(f.value, self.aliases, self.shadow)
            if ln is not None and ln in self.held:
                self.held.remove(ln)

        # thread creation not bound by an Assign (e.g. Thread(...).start())
        if _is_factory(e, {"Thread"}):
            self.an.note_thread(e, None, self.qual)

        # join/shutdown bookkeeping for RACE004
        if fattr == "join" and not e.args and isinstance(f, ast.Attribute):
            key = self._binding_key(f.value)
            if key:
                self.an.joined.add(key)
            self.an.joined.add(("func", self.qual))

        # interprocedural: self._helper(...) call sites
        if recv_attr is None and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" and \
                f.attr in self.owner.methods:
            self.owner.call_sites.setdefault(f.attr, []).append(
                frozenset(self.held))
            self.owner.callers.setdefault(f.attr, set()).add(self.qual)

        if self.held and self.collect:
            self._blocking(e, f, fattr, fname, recv_attr)
            self._callback(e, f, fattr, fname, recv_attr)

        for a in e.args:
            self.expr(a)
        for k in e.keywords:
            self.expr(k.value)
        if isinstance(f, ast.Attribute):
            self.expr(f.value)
        elif not isinstance(f, ast.Name):
            self.expr(f)

    def _blocking(self, e, f, fattr, fname, recv_attr):
        held = " while holding %s" % ", ".join(
            self.owner.prefix(h) for h in self.held)
        reason = None
        if fattr == "sleep" or fname == "sleep":
            reason = "sleep()"
        elif fattr == "maybe_inject" or fname == "maybe_inject":
            reason = "chaos.maybe_inject() — a chaos fault can delay " \
                     "or raise here"
        elif fattr in _BLOCKING_IO:
            reason = "blocking I/O .%s()" % fattr
        elif fattr == "wait":
            # cv.wait() releases the cv it waits on: only the *sole*
            # held lock being the waited condition is safe
            if not (recv_attr is not None and self.held and
                    recv_attr == self.held[-1] and len(self.held) == 1):
                reason = ".wait() that does not release the held lock"
        elif fattr in _BLOCKING_NOARG and not e.args and \
                not any(k.arg in ("timeout", "block") for k in e.keywords):
            reason = "unbounded .%s()" % fattr
        elif fattr in ("run", "call", "Popen") and \
                isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "subprocess":
            reason = "subprocess.%s()" % fattr
        if reason is not None:
            self.an.emit("RACE003", e.lineno,
                         "%s%s: every thread contending for that lock "
                         "stalls behind this call (and a chaos "
                         "delay/raise under a lock becomes a "
                         "server-wide stall)" % (reason, held))

    def _callback(self, e, f, fattr, fname, recv_attr):
        cb = None
        if recv_attr is not None and fattr is None:
            pass
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            a = f.attr
            if a not in self.owner.methods and \
                    (a in self.owner.foreign or _CALLBACK_NAME.search(a)):
                cb = "self.%s" % a
        elif isinstance(f, ast.Name):
            al = self.aliases.get(f.id)
            if al is not None and al[0] == "cb":
                cb = f.id
            elif al is not None and al[0] == "attr" and \
                    al[1] not in self.owner.methods and \
                    (al[1] in self.owner.foreign or
                     _CALLBACK_NAME.search(al[1])):
                cb = f.id
            elif _CALLBACK_NAME.search(f.id):
                cb = f.id
        if cb is not None:
            self.an.emit("RACE005", e.lineno,
                         "callback %s(...) invoked while holding %s: a "
                         "user/foreign callable under the owner's lock "
                         "can call back in (deadlock) or block the owner "
                         "unboundedly — copy state under the lock, call "
                         "outside (the heartbeat-watchdog fix)"
                         % (cb, ", ".join(self.owner.prefix(h)
                                          for h in self.held)))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _line_suppressions(source):
    from .source_lint import _line_suppressions as impl
    return impl(source)


def _analyze_source(source, filename):
    """-> (findings, edges, owners) for one module."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        raise ValueError("cannot parse %s: %s" % (filename, e))
    an = _Analyzer(tree, filename, _line_suppressions(source))
    an.run()
    return an.findings, an.edges, an.owners


def lint_race_source(source, filename="<string>", disable=()):
    """Race-lint one module's source text: the per-module rules
    (RACE001/003/004/005) plus cycle detection over the module's own
    lock-order edges.  The cross-module hierarchy sync runs in
    :func:`lint_threaded_sources`."""
    findings, edges, _ = _analyze_source(source, filename)
    findings = findings + lock_order_findings(edges)
    return filter_findings(findings, disable)


def lint_race_file(path, disable=()):
    with open(path) as f:
        return lint_race_source(f.read(), filename=path, disable=disable)


def _dedup_edges(edges):
    """(outer, inner) -> first site, deterministically."""
    out = {}
    for outer, inner, site in sorted(edges):
        out.setdefault((outer, inner), site)
    return out


def parse_hierarchy(path):
    """The pinned lock-order rows of docs/concurrency.md: markdown
    table rows whose 2nd and 3rd columns are backticked lock names
    (``| n | `Outer.lock` | `Inner.lock` | where |``)."""
    with open(path) as f:
        text = f.read()
    return [(m.group(1), m.group(2)) for m in re.finditer(
        r"^\|[^|`]*\|\s*`([A-Za-z_][\w.()\[\]]*)`\s*\|"
        r"\s*`([A-Za-z_][\w.()\[\]]*)`\s*\|", text, re.M)]


def _sccs(nodes, adj):
    """Tarjan SCCs, deterministic order."""
    index, low, on, stack, out = {}, {}, set(), [], []
    counter = [0]

    def strong(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                on.discard(w)
                scc.append(w)
                if w == v:
                    break
            out.append(sorted(scc))

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


def lock_order_findings(edges, hierarchy_path=None, disable=()):
    """RACE002 over acquired-while-holding edges: cycles are potential
    deadlocks; when ``hierarchy_path`` is given, the docs table must
    match the observed edge set both ways."""
    findings = []
    dedup = _dedup_edges(edges)
    nodes = set()
    adj = {}
    for (outer, inner), _site in dedup.items():
        nodes.add(outer)
        nodes.add(inner)
        adj.setdefault(outer, []).append(inner)
    for k in adj:
        adj[k].sort()
    for scc in _sccs(nodes, adj):
        cyclic = len(scc) > 1 or (scc[0] in adj.get(scc[0], ()))
        if not cyclic:
            continue
        sites = sorted(site for (o, i), site in dedup.items()
                       if o in scc and i in scc)
        findings.append(Finding(
            "RACE002", sites[0] if sites else "lock-order",
            "potential deadlock: lock-order cycle through %s (acquire "
            "sites: %s) — two threads entering the cycle from different "
            "ends block each other forever; pick one order and pin it "
            "in docs/concurrency.md" % (" -> ".join(scc),
                                        ", ".join(sites))))
    if hierarchy_path is not None and os.path.isfile(hierarchy_path):
        pinned = set(parse_hierarchy(hierarchy_path))
        observed = set(dedup)
        for outer, inner in sorted(observed - pinned):
            findings.append(Finding(
                "RACE002", dedup[(outer, inner)],
                "acquired-while-holding edge %s -> %s is not pinned in "
                "the docs/concurrency.md lock-hierarchy table — add the "
                "row (same PR) or fix the nesting" % (outer, inner)))
        for outer, inner in sorted(pinned - observed):
            findings.append(Finding(
                "RACE002", "docs/concurrency.md",
                "pinned lock-order row %s -> %s is no longer observed "
                "in the swept sources — drop the stale row so the table "
                "stays the single source of truth" % (outer, inner)))
    return filter_findings(findings, disable)


def _repo_root():
    pkg = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(pkg)            # mxnet_tpu/
    repo = os.path.dirname(root)
    if not os.path.isfile(os.path.join(root, "kvstore_ps.py")):
        return None
    return repo


def threaded_targets():
    """The swept modules, repo-relative and sorted: every threaded host
    tier (ISSUE 16) — PS server/client, serving, resilience, io,
    telemetry, mlops, the engine, and the tools/ CLIs."""
    repo = _repo_root()
    if repo is None:
        return []
    rels = ["mxnet_tpu/engine.py", "mxnet_tpu/kvstore_ps.py",
            "mxnet_tpu/kvstore_server.py"]
    for pkg in ("io", "mlops", "resilience", "serving", "telemetry"):
        d = os.path.join(repo, "mxnet_tpu", pkg)
        if os.path.isdir(d):
            rels += ["mxnet_tpu/%s/%s" % (pkg, f)
                     for f in os.listdir(d) if f.endswith(".py")]
    tools = os.path.join(repo, "tools")
    if os.path.isdir(tools):
        rels += ["tools/%s" % f for f in os.listdir(tools)
                 if f.endswith(".py")]
    return sorted(r for r in rels
                  if os.path.isfile(os.path.join(repo, r)))


def _sweep_once():
    """-> (per-file findings, edges, owners-by-file), repo-relative."""
    repo = _repo_root()
    findings, edges, owners = [], [], []
    if repo is None:
        return findings, edges, owners
    for rel in threaded_targets():
        with open(os.path.join(repo, rel)) as f:
            source = f.read()
        try:
            found, es, own = _analyze_source(source, rel)
        except ValueError:
            continue
        findings += found
        edges += es
        owners.append((rel, own))
    return findings, edges, owners


def lint_threaded_sources(disable=(), hierarchy=None):
    """The mxrace sweep ``--self-check`` runs: every threaded host
    module race-linted, the lock-order graph checked for cycles and
    synced against the docs/concurrency.md hierarchy table both ways,
    and the whole report checked for determinism (two analyses of the
    same sources must agree — the COST003 contract)."""
    repo = _repo_root()
    if repo is None:
        return []
    findings, edges, _owners = _sweep_once()
    if hierarchy is None:
        hierarchy = os.path.join(repo, "docs", "concurrency.md")
    findings = findings + lock_order_findings(edges, hierarchy)
    f2, e2, _ = _sweep_once()
    f2 = f2 + lock_order_findings(e2, hierarchy)
    if [str(f) for f in findings] != [str(f) for f in f2]:
        findings.append(Finding(
            "COST003", "race_self_check",
            "two runs of the race pass over the same sources produced "
            "different reports — the race gate would flap in CI"))
    return filter_findings(findings, disable)


def race_summary(hierarchy=None):
    """The ``--json`` ``race`` section (schema_version 5): the sweep's
    lock inventory, the inferred guard map, the deduplicated
    acquired-while-holding edges and the pinned hierarchy —
    deterministically ordered throughout."""
    repo = _repo_root()
    if repo is None:
        return {"n_files": 0, "locks": [], "guards": {}, "edges": [],
                "hierarchy": []}
    _findings, edges, owners = _sweep_once()
    locks, guards = set(), {}
    for _rel, owns in owners:
        for o in owns:
            for l in o.locks:
                locks.add(o.prefix(l))
            for m in o.lock_methods:
                locks.add(o.prefix(m + "()"))
            by_attr = {}
            for acc in o.accesses:
                by_attr.setdefault(acc.attr, []).append(acc)
            for attr in sorted(by_attr):
                guard, lw = _inferred_guard(o, by_attr[attr])
                if not lw:
                    continue
                if guard:
                    guards[o.prefix(attr)] = sorted(
                        o.prefix(g) for g in guard)
    dedup = _dedup_edges(edges)
    if hierarchy is None:
        hierarchy = os.path.join(repo, "docs", "concurrency.md")
    pinned = parse_hierarchy(hierarchy) \
        if os.path.isfile(hierarchy) else []
    return {
        "n_files": len(owners),
        "locks": sorted(locks),
        "guards": {k: guards[k] for k in sorted(guards)},
        "edges": [{"outer": o, "inner": i, "site": s}
                  for (o, i), s in sorted(dedup.items())],
        "hierarchy": [[o, i] for o, i in sorted(set(pinned))],
    }
