"""Network visualization: print_summary / plot_network.

Reference: ``python/mxnet/visualization.py`` — tabular summary with
parameter counts, and a graphviz dot graph (rendered only if graphviz is
installed; gated import since it is not a baked-in dependency).
"""
from __future__ import annotations

import json

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Print a layer table with output shapes and parameter counts
    (reference: visualization.py print_summary)."""
    if not isinstance(shape, dict) and shape is not None:
        raise ValueError("shape must be a dict of name->shape")
    show_shape = shape is not None
    shape_dict = {}
    if show_shape:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape_partial(**shape)
        if out_shapes is None:
            raise MXNetError("cannot infer shapes")
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {e[0] for e in conf["heads"]}
    positions = [int(line_length * p) for p in positions]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(["Layer (type)", "Output Shape", "Param #", "Previous Layer"],
              positions)
    print("=" * line_length)
    total_params = 0

    def print_layer_summary(node, out_shape):
        nonlocal total_params
        op = node["op"]
        name = node["name"]
        pre_nodes = [nodes[i[0]]["name"] for i in node["inputs"]
                     if nodes[i[0]]["op"] != "null"]
        cur_param = 0
        attrs = node.get("attrs", {})
        for i in node["inputs"]:
            inode = nodes[i[0]]
            if inode["op"] == "null" and ("weight" in inode["name"] or
                                          "bias" in inode["name"] or
                                          "gamma" in inode["name"] or
                                          "beta" in inode["name"]):
                s = shape_dict.get(inode["name"])
                if s:
                    p = 1
                    for d in s:
                        p *= d
                    cur_param += p
        first = "%s(%s)" % (name, op)
        print_row([first, out_shape or "", cur_param,
                   ",".join(pre_nodes[:1])], positions)
        total_params += cur_param

    for i, node in enumerate(nodes):
        if node["op"] == "null":
            if show_shape and i in heads:
                pass
            continue
        key = node["name"] + "_output"
        out_shape = shape_dict.get(key, shape_dict.get(node["name"]))
        print_layer_summary(node, out_shape)
        print("_" * line_length)
    print("Total params: %s" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the symbol (reference: visualization.py
    plot_network).  Requires the optional `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the optional graphviz package") from e
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    if node_attrs:
        node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    hidden = set()
    for i, node in enumerate(nodes):
        name = node["name"]
        op = node["op"]
        if op == "null":
            if hide_weights and any(s in name for s in
                                    ("weight", "bias", "gamma", "beta",
                                     "moving_mean", "moving_var")):
                hidden.add(i)
                continue
            dot.node(name=name, label=name, fillcolor="#8dd3c7")
        else:
            label = "%s\n%s" % (op, name)
            color = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
                     "BatchNorm": "#bebada", "Activation": "#ffffb3",
                     "Pooling": "#80b1d3", "Concat": "#fdb462",
                     "Flatten": "#fdb462", "Reshape": "#fdb462",
                     "Softmax": "#fccde5", "SoftmaxOutput": "#fccde5",
                     }.get(op, "#b3de69")
            dot.node(name=name, label=label, fillcolor=color)
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for j in node["inputs"]:
            if j[0] in hidden:
                continue
            src = nodes[j[0]]["name"]
            dot.edge(tail_name=src, head_name=node["name"])
    return dot
