"""Legacy model API: checkpointing + FeedForward.

Reference: ``python/mxnet/model.py`` — ``save_checkpoint:384`` /
``load_checkpoint:414`` (prefix-symbol.json + prefix-####.params with
arg:/aux: key prefixes), ``_create_kvstore:77`` (decides update_on_kvstore),
``FeedForward:452`` (pre-Module training class, kept for script parity).
"""
from __future__ import annotations

import logging

import numpy as _np

from . import ndarray as nd
from . import symbol as sym
from .base import MXNetError
from .io import DataBatch, NDArrayIter
from .ndarray import NDArray
from .serialization import load_ndarrays, save_ndarrays

__all__ = ["save_checkpoint", "load_checkpoint", "load_params", "save_params",
           "FeedForward", "BatchEndParam"]

from .module.base_module import BatchEndParam  # noqa: F401  (re-export)


def _create_kvstore(kvstore, num_device, arg_params):
    """Decide (kvstore, update_on_kvstore) — reference: model.py:77.

    On TPU a single jitted program already aggregates gradients across the
    mesh (GSPMD psum), so a kvstore is only created when explicitly
    requested or when running multi-host."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(_np.prod(p.shape)) for p in
                               arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def save_params(fname, arg_params, aux_params=None, format="mxtpu"):
    data = {"arg:%s" % k: v for k, v in (arg_params or {}).items()}
    data.update({"aux:%s" % k: v for k, v in (aux_params or {}).items()})
    save_ndarrays(fname, data, format=format)


def load_params(fname):
    loaded = load_ndarrays(fname)
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    format="mxtpu"):
    """Write prefix-symbol.json + prefix-####.params
    (reference: model.py:384).  format="mxnet" emits the reference
    dmlc-stream .params so stock MXNet can load the checkpoint."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_params("%s-%04d.params" % (prefix, epoch), arg_params, aux_params,
                format=format)
    logging.info("Saved checkpoint to \"%s-%04d.params\"", prefix, epoch)


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) — reference: model.py:414."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params("%s-%04d.params" % (prefix, epoch))
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training class (reference: model.py:452) — a thin veneer over
    Module kept so pre-Module reference scripts run."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        if initializer is None:
            from .initializer import Uniform
            initializer = Uniform(0.01)
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    def _as_iter(self, X, y=None, batch_size=None, shuffle=False):
        from .io import DataIter
        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size or self.numpy_batch_size,
                           shuffle=shuffle)

    def _label_names(self, train_data):
        if getattr(train_data, "provide_label", None):
            return [d.name for d in train_data.provide_label]
        return ["softmax_label"]

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .module.module import Module
        train_data = self._as_iter(X, y, shuffle=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = self._as_iter(eval_data[0], eval_data[1])
        mod = Module(self.symbol,
                     data_names=[d.name for d in train_data.provide_data],
                     label_names=self._label_names(train_data),
                     context=self.ctx)
        self._module = mod
        opt_params = {k: v for k, v in self.kwargs.items()}
        opt_params.setdefault("learning_rate", 0.01)
        mod.fit(train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=opt_params,
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, allow_missing=True,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._as_iter(X)
        from .module.module import Module
        if self._module is None:
            mod = Module(self.symbol,
                         data_names=[d.name for d in data.provide_data],
                         label_names=None, context=self.ctx)
            mod.bind(data.provide_data, for_training=False)
            mod.init_params(arg_params=self.arg_params,
                            aux_params=self.aux_params, allow_missing=False)
            self._module = mod
        out = self._module.predict(data, num_batch=num_batch, reset=reset)
        if isinstance(out, list):
            return [o.asnumpy() for o in out]
        return out.asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None):
        data = self._as_iter(X)
        from .module.module import Module
        mod = Module(self.symbol,
                     data_names=[d.name for d in data.provide_data],
                     label_names=self._label_names(data), context=self.ctx)
        mod.bind(data.provide_data, data.provide_label, for_training=False)
        mod.init_params(arg_params=self.arg_params, aux_params=self.aux_params)
        res = mod.score(data, eval_metric, num_batch=num_batch)
        return res[0][1]
