"""Mixed-precision policy: bf16 compute with f32 masters + dynamic loss
scaling (ROADMAP item 5a, arxiv 2004.13336's motivating layout).

One module owns every precision decision so the three trainer spellings
(replicated, ``zero=1``, PR-14 mesh) and the analysis tier agree:

- **dtype policy** — :func:`resolve_dtype` maps the trainer's
  ``dtype=`` knob to a compute dtype.  Under ``bf16`` the *params and
  activations* are bfloat16; the f32 **master weights** exist only as
  the ZeRO-1 flat shard (``parallel/zero.py`` keeps them as a state
  leaf, physically ``P(axis)``-sharded — they never materialize
  unsharded) or, for the replicated spelling, as the ordinary f32
  ``train_vals`` cast to bf16 at the forward boundary.
- **gradient reduction dtype** — gradients are cast to f32 BEFORE the
  cross-replica collective (psum / psum_scatter).  A bf16 ring
  reduction loses ~3 decimal digits per hop; the tightened DST004 rule
  (``analysis/dist_lint.py``) fails the gate when a sub-f32 float is
  reduced over the data axis.  ``PRECISION_F32_GRAD_REDUCE`` is the
  mutation seam proving that gate bites.
- **dynamic loss scaling** — the classic grow/backoff machine
  (:func:`loss_scale_update`): multiply the loss by ``scale`` so bf16
  gradients don't flush to zero, unscale inside the fused optimizer
  kernel (``ops/fused_optimizer.py`` reads ``[lr, inv_scale, ok]`` from
  SMEM — unscale+clip+update stays ONE kernel pass), skip the step and
  halve the scale on inf/nan, double it after ``GROWTH_INTERVAL``
  consecutive finite steps.  Skipped steps are select-skips: the kernel
  writes back the OLD weights/state, so a skipped step is a true no-op.
- **telemetry** — :func:`record_loss_scale` publishes the live scale
  (``mxtpu_loss_scale`` gauge) and the skipped-step total
  (``mxtpu_loss_scale_skipped_steps_total`` counter) through the PR-9
  registry (docs/observability.md).

``PRECISION_MASTER_F32`` is the budget-gate mutation seam
(``parallel/zero.py`` ``ZERO1_RUNTIME_ALL_GATHER`` discipline): flipping
it False makes the bf16 ZeRO-1 update re-derive its "masters" from the
bf16 params via the full flat f32 spelling — masters materialize
unsharded and the ``bf16_zero1_train_step`` row's pinned peak-HBM drop
vs the f32 twin fails (COST001, rc=2; tests/test_precision.py,
subprocess).  Production code never touches either seam.
"""
from __future__ import annotations

__all__ = ["PRECISION_MASTER_F32", "PRECISION_F32_GRAD_REDUCE",
           "LOSS_SCALE_INIT", "GROWTH_FACTOR", "BACKOFF_FACTOR",
           "GROWTH_INTERVAL", "MAX_SCALE", "MIN_SCALE", "resolve_dtype",
           "is_reduced", "init_loss_scale", "all_finite",
           "loss_scale_update", "record_loss_scale"]

# budget-gate mutation seams (module docstring) — flipped only by tests
PRECISION_MASTER_F32 = True
PRECISION_F32_GRAD_REDUCE = True

# the loss-scale state machine's pinned constants (docs/precision.md)
LOSS_SCALE_INIT = 2.0 ** 15
GROWTH_FACTOR = 2.0
BACKOFF_FACTOR = 0.5
GROWTH_INTERVAL = 200
MAX_SCALE = 2.0 ** 24
MIN_SCALE = 1.0

_ALIASES = {"f32": "float32", "fp32": "float32", "float32": "float32",
            "bf16": "bfloat16", "bfloat16": "bfloat16"}


def resolve_dtype(spec):
    """The trainer's ``dtype=`` knob -> a jnp dtype (``float32`` /
    ``bfloat16``).  ``None`` means float32 (the historical default)."""
    import jax.numpy as jnp

    if spec is None:
        return jnp.float32
    if isinstance(spec, str):
        name = _ALIASES.get(spec.lower())
        if name is None:
            raise ValueError("dtype must be one of %s, got %r"
                             % (sorted(set(_ALIASES)), spec))
        return jnp.dtype(name)
    dt = jnp.dtype(spec)
    if dt not in (jnp.dtype("float32"), jnp.dtype("bfloat16")):
        raise ValueError("dtype must be float32 or bfloat16, got %r"
                         % (spec,))
    return dt


def is_reduced(dtype):
    """True when ``dtype`` is a sub-f32 compute dtype (loss scaling and
    master weights apply)."""
    import jax.numpy as jnp

    return jnp.dtype(dtype) == jnp.dtype("bfloat16")


def init_loss_scale(init=LOSS_SCALE_INIT):
    """``(scale, good_steps)`` — the device-resident loss-scale state:
    f32 scalar scale, i32 consecutive-finite-step counter."""
    import jax.numpy as jnp

    return (jnp.asarray(init, jnp.float32), jnp.asarray(0, jnp.int32))


def all_finite(leaves):
    """Traced scalar bool: every element of every leaf is finite.  The
    per-step inf/nan probe the loss-scale machine keys on; cheap (one
    O(n) reduction already fused into the grad pass by XLA)."""
    import jax.numpy as jnp

    leaves = list(leaves)
    if not leaves:
        return jnp.asarray(True)
    flags = [jnp.isfinite(leaf).all() for leaf in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


def loss_scale_update(scale, good_steps, grads_finite,
                      growth_factor=GROWTH_FACTOR,
                      backoff_factor=BACKOFF_FACTOR,
                      growth_interval=GROWTH_INTERVAL,
                      max_scale=MAX_SCALE, min_scale=MIN_SCALE):
    """One tick of the grow/backoff machine (pure, traced):

    - finite grads: ``good_steps += 1``; after ``growth_interval``
      consecutive finite steps the scale doubles (capped at
      ``max_scale``) and the counter resets;
    - non-finite grads: the step is skipped, the scale halves (floored
      at ``min_scale``), the counter resets.

    Returns ``(new_scale, new_good_steps)``.  The caller derives
    "skipped" from ``grads_finite`` itself (see
    ``DataParallelTrainer``'s skipped-step counter)."""
    import jax.numpy as jnp

    scale = jnp.asarray(scale, jnp.float32)
    good = jnp.asarray(good_steps, jnp.int32)
    fin = jnp.asarray(grads_finite, bool)
    grown_now = jnp.logical_and(fin, good + 1 >= growth_interval)
    new_scale = jnp.where(
        fin,
        jnp.where(grown_now,
                  jnp.minimum(scale * growth_factor, max_scale), scale),
        jnp.maximum(scale * backoff_factor, min_scale))
    new_good = jnp.where(jnp.logical_and(fin, jnp.logical_not(grown_now)),
                         good + 1, jnp.asarray(0, jnp.int32))
    return new_scale, new_good


def record_loss_scale(scale, skipped_delta=0, run_id=None):
    """Publish the live scale and any newly-skipped steps through the
    telemetry registry (host values — call outside traced code)."""
    from .telemetry.metrics import registry

    labels = {"run_id": run_id} if run_id else {}
    registry().gauge(
        "mxtpu_loss_scale",
        "current dynamic loss scale (mixed-precision training)"
    ).set(float(scale), **labels)
    if skipped_delta:
        registry().counter(
            "mxtpu_loss_scale_skipped_steps_total",
            "optimizer steps skipped on non-finite gradients"
        ).inc(int(skipped_delta), **labels)
