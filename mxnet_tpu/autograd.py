"""Imperative autograd: record/pause scopes + a tape over jax.vjp.

Reference: ``src/imperative/imperative.cc`` (RecordOp:183 builds an nnvm graph
on NDArray ``entry_``; Backward runs pass::Gradient) and the Python surface
``python/mxnet/autograd.py:122-181,243,270``.  Here each recorded op call runs
through ``jax.vjp`` once — forward result plus a vjp closure — so the "tape"
is a DAG of vjp closures; Backward is a reverse-topological sweep feeding
cotangents through them.  ``create_graph=True`` re-records the vjp calls
themselves (vjp-of-vjp), giving higher-order gradients where the reference
re-runs pass::Gradient on the backward graph.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "set_recording", "set_training", "mark_variables",
    "backward", "grad", "get_symbol", "Function",
]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _st().recording = bool(is_record)
    return prev


def set_training(train_mode_):
    prev = _st().training
    _st().training = bool(train_mode_)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode_):
        self._enter_record = is_record
        self._enter_train = train_mode_
        self._prev = None

    def __enter__(self):
        s = _st()
        self._prev = (s.recording, s.training)
        if self._enter_record is not None:
            s.recording = self._enter_record
        if self._enter_train is not None:
            s.training = self._enter_train
        return self

    def __exit__(self, *exc):
        s = _st()
        s.recording, s.training = self._prev


def record(train_mode=True):
    """Scope: operations are recorded for gradient (autograd.py:122)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------
class Node:
    """One recorded op: a vjp closure plus its input NDArrays.

    fwd_fn/in_raw/fwd_multi are kept so create_graph=True can re-derive the
    vjp *as a function of the primals* (higher-order grads); a vjp closure
    alone treats the primals as constants.
    """

    __slots__ = ("vjp_fn", "inputs", "out_avals", "id", "fwd_fn", "in_raw",
                 "fwd_multi")
    _counter = [0]

    def __init__(self, vjp_fn, inputs, out_avals, fwd_fn=None, in_raw=None,
                 fwd_multi=False):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list of NDArray (differentiable inputs)
        self.out_avals = out_avals    # [(shape, dtype)] for zero-cotangent fill
        self.fwd_fn = fwd_fn
        self.in_raw = in_raw
        self.fwd_multi = fwd_multi
        Node._counter[0] += 1
        self.id = Node._counter[0]


def record_op(vjp_fn, inputs, out_arrays, fwd_fn=None, in_raw=None,
              fwd_multi=False):
    avals = [(o.shape, o.dtype) for o in out_arrays]
    return Node(vjp_fn, list(inputs), avals, fwd_fn, in_raw, fwd_multi)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Mark NDArrays as autograd leaves (reference: imperative.h:121)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._mark = req != "null"
        v._grad_req = req
        v._grad = g
        v._entry = None


def _toposort(head_nodes):
    order = []
    seen = set()
    stack = [(n, False) for n in head_nodes]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if node.id in seen:
            continue
        seen.add(node.id)
        stack.append((node, True))
        for inp in node.inputs:
            ent = inp._entry
            if ent is not None and ent[0].id not in seen:
                stack.append((ent[0], False))
    return order  # children before parents (reverse-topo for backward)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True,
             create_graph=False):
    """Compute gradients of heads w.r.t. marked variables
    (reference: python/mxnet/autograd.py:243 + imperative.cc Backward)."""
    from .ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    from .ndarray import NDArray

    # cotangent buckets: node.id -> [cotangent or None per output].
    # Under create_graph the cotangents are NDArrays so the chain of backward
    # computations stays recorded (needed for higher-order grads).
    buckets = {}
    leaf_acc = {}  # id(leaf) -> [leaf, summed grad (NDArray when create_graph)]
    head_nodes = []
    for h, hg in zip(heads, head_grads):
        g = hg._data if hasattr(hg, "_data") else (
            jnp.ones(h.shape, h.dtype) if hg is None else jnp.asarray(hg))
        if create_graph:
            g = NDArray(g)
        if h._entry is None:
            if getattr(h, "_mark", False):
                _leaf_add(leaf_acc, h, g)
                continue
            # fail loudly like the reference (imperative.cc Backward:
            # "cannot differentiate a variable that was not recorded")
            from .base import MXNetError
            raise MXNetError(
                "cannot run backward on an array computed outside "
                "autograd.record() (no gradient graph attached)")
        node, idx = h._entry
        head_nodes.append(node)
        slot = buckets.setdefault(node.id, [None] * len(node.out_avals))
        slot[idx] = g if slot[idx] is None else slot[idx] + g

    order = _toposort(head_nodes) if head_nodes else []
    for node in reversed(order):
        cots = buckets.pop(node.id, None)
        if cots is None:
            continue
        if create_graph:
            cot_nds = [
                c if c is not None else NDArray(jnp.zeros(shape, dtype))
                for c, (shape, dtype) in zip(cots, node.out_avals)
            ]
            in_grads = _recorded_vjp(node, cot_nds)
        else:
            full = tuple(
                c if c is not None else jnp.zeros(shape, dtype)
                for c, (shape, dtype) in zip(cots, node.out_avals)
            )
            in_grads = node.vjp_fn(full)
        for inp, ig in zip(node.inputs, in_grads):
            if ig is None:
                continue
            ent = inp._entry
            if ent is not None:
                pnode, pidx = ent
                slot = buckets.setdefault(pnode.id, [None] * len(pnode.out_avals))
                if not create_graph and hasattr(ig, "_data"):
                    ig = ig._data
                slot[pidx] = ig if slot[pidx] is None else slot[pidx] + ig
            elif getattr(inp, "_mark", False):
                _leaf_add(leaf_acc, inp, ig)

    for leaf, g in leaf_acc.values():
        _write_leaf_grad(leaf, g)


def _leaf_add(acc, leaf, g):
    key = id(leaf)
    if key in acc:
        prev = acc[key][1]
        acc[key][1] = prev + g  # NDArray + NDArray stays recorded if create_graph
    else:
        acc[key] = [leaf, g]


def _write_leaf_grad(leaf, g):
    from .ndarray import NDArray

    is_nd = isinstance(g, NDArray)
    raw = g._data if is_nd else g
    if leaf._grad is None:
        leaf._grad = NDArray(jnp.zeros_like(leaf._data))
    if leaf._grad_req == "add":
        leaf._grad._set_data(leaf._grad._data + raw)
    elif is_nd and g._entry is not None:
        # create_graph path: keep the recorded entry on the grad array
        leaf._grad._data = raw
        leaf._grad._entry = g._entry
    else:
        leaf._grad._set_data(raw)


def _recorded_vjp(node, cot_nds):
    """Apply the node's backward while recording it as new graph nodes, so the
    produced gradients are themselves differentiable (higher-order)."""
    from .ndarray import NDArray

    cotangents = tuple(c._data for c in cot_nds)
    if node.fwd_fn is None:
        # custom Function — backward not re-differentiable (as in reference)
        return node.vjp_fn(cotangents)

    n_in = len(node.in_raw)
    fwd_fn, fwd_multi = node.fwd_fn, node.fwd_multi

    def gfn(*args):
        prim, cots = args[:n_in], args[n_in:]
        _, vjp = jax.vjp(fwd_fn, *prim)
        return tuple(vjp(tuple(cots) if fwd_multi else cots[0]))

    all_raw = tuple(node.in_raw) + tuple(cotangents)
    outs, vjp2 = jax.vjp(gfn, *all_raw)
    new_inputs = list(node.inputs) + cot_nds
    new_node = record_op(vjp2, new_inputs, list(outs), gfn, list(all_raw), True)
    out_nds = []
    for i, o in enumerate(outs):
        nd_ = NDArray(o)
        nd_._entry = (new_node, i)
        out_nds.append(nd_)
    return out_nds


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Like backward but returns grads of `variables` instead of writing .grad
    (reference: autograd.py:270)."""
    from .ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    saved = [(v._mark, v._grad_req, v._grad, v._entry) for v in variables]
    for v in variables:
        v._mark = True
        v._grad_req = "write"
        v._grad = None
        # keep entry: interior nodes allowed for grad()
    prev_rec = set_recording(True) if create_graph else None
    try:
        backward(heads, head_grads, retain_graph=True, train_mode=train_mode,
                 create_graph=create_graph)
    finally:
        if prev_rec is not None:
            set_recording(prev_rec)
    outs = []
    for v, (m, gr, og, ent) in zip(variables, saved):
        if v._grad is None:
            raise ValueError("some variables do not participate in the graph")
        outs.append(v._grad)
        v._mark, v._grad_req, v._grad, v._entry = m, gr, og, ent
    return outs[0] if single else outs


def get_symbol(x):
    """Reference autograd.get_symbol returns the recorded graph as a Symbol.
    We return None placeholder symbol support lives in mxnet_tpu.symbol."""
    raise NotImplementedError("use mxnet_tpu.symbol to build symbolic graphs")


# ---------------------------------------------------------------------------
# Custom differentiable Function (reference: autograd.py:363 class Function)
# ---------------------------------------------------------------------------
class Function:
    """User-defined differentiable function with explicit forward/backward.

    Subclass and implement forward(self, *inputs) and backward(self, *ograds),
    both over NDArrays, as in the reference API.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn = self

            def vjp_fn(cotangents):
                with pause():
                    grads = fn.backward(*[NDArray(c) for c in cotangents])
                if not isinstance(grads, (list, tuple)):
                    grads = [grads]
                return [g._data if hasattr(g, "_data") else g for g in grads]

            diff_inputs = [i for i in inputs if isinstance(i, NDArray)]
            node = record_op(vjp_fn, diff_inputs, [o._data for o in outs])
            for i, o in enumerate(outs):
                o._entry = (node, i)
        return outs[0] if single else outs
