"""Host-only run-ahead overlap micro-bench: ``python -m
mxnet_tpu.engine_bench``.

Measures what the async dispatch engine buys: wall time of a *stepped*
training loop (feed → step → per-step ``float(loss)`` fetch, fully
serialized — the pre-engine ``DataParallelTrainer`` behaviour) against
the *bulk* loop (``PrefetchToDeviceIter`` ships batch k+1 on a thread
while step k executes, ``engine.bulk(depth)`` keeps the dispatch queue
full, the loss accumulates device-resident and is fetched once).

Run as a ``JAX_PLATFORMS=cpu`` subprocess by bench.py BEFORE backend
acquisition (the PR-2/PR-4 pattern), so ``train_loop_overlap_ratio``
stays live when the TPU is down.  The host feed latency is simulated
with a calibrated sleep equal to the measured device step time — the
stand-in for the multi-process shm pipeline, whose decode cost is paid
in worker *processes*, not on this thread (io/pipeline.py).  With feed
≈ step, a perfectly overlapped loop approaches 2× the stepped one; the
CI gate asserts ≥ 1.3×.

Prints one JSON line; bench.py merges it into the round record.
"""
from __future__ import annotations

import json
import os
import time


class _SlowFeedIter:
    """Host iterator with a fixed per-batch latency (decode stand-in)."""

    def __init__(self, inner, delay_s):
        self.inner = inner
        self.delay_s = delay_s
        self.batch_size = inner.batch_size

    @property
    def provide_data(self):
        return self.inner.provide_data

    @property
    def provide_label(self):
        return self.inner.provide_label

    def reset(self):
        self.inner.reset()

    def next(self):
        batch = self.inner.next()  # raises StopIteration at epoch end
        time.sleep(self.delay_s)
        return batch

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()


def main():
    import jax
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import engine, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.io import NDArrayIter, PrefetchToDeviceIter
    from mxnet_tpu.parallel import DataParallelTrainer

    steps = int(os.environ.get("MXTPU_OVERLAP_STEPS", "24"))
    depth = int(os.environ.get("MXTPU_OVERLAP_DEPTH", "4"))
    batch = int(os.environ.get("MXTPU_OVERLAP_BATCH", "128"))
    # big enough that the device step dwarfs the fixed per-step python
    # dispatch cost (~5ms on the 1-core CI host, GIL-held, un-overlappable)
    # — the regime every real model is in
    hidden = int(os.environ.get("MXTPU_OVERLAP_HIDDEN", "1024"))
    feat = 784

    rng = np.random.RandomState(0)
    X = rng.rand(steps * batch, feat).astype(np.float32)
    y = (np.arange(steps * batch) % 10).astype(np.float32)

    def build_trainer():
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(hidden, activation="relu"),
                nn.Dense(hidden, activation="relu"),
                nn.Dense(10))
        net.initialize(mx.init.Xavier())
        return DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05})

    # -- calibrate: compile the step and measure its synchronous latency
    tr = build_trainer()
    xb = mx.nd.array(X[:batch])
    yb = mx.nd.array(y[:batch])
    tr.step(xb, yb).wait_to_read()  # compile
    t0 = time.perf_counter()
    calib_iters = 8
    for _ in range(calib_iters):
        loss = tr.step(xb, yb)
        loss.wait_to_read()
    step_s = (time.perf_counter() - t0) / calib_iters
    # feed == step: the balanced-pipeline regime where serialization
    # costs the most (2x) and overlap pays the most.  The sleep is
    # GIL-free, so it overlaps with the XLA compute threads even on the
    # 1-core CI host — exactly like the real shm pipeline, whose decode
    # burns worker-process CPUs, not this thread's.
    feed_s = step_s * float(os.environ.get("MXTPU_OVERLAP_FEED_MULT",
                                           "1.0"))

    def make_iter():
        return _SlowFeedIter(NDArrayIter(X, y, batch,
                                         last_batch_handle="discard"),
                             feed_s)

    # -- stepped: the pre-engine loop — feed, step, fetch, every batch.
    # The per-step fetch is the deliberate baseline under test, not a
    # recommendation.
    tr = build_trainer()
    tr.step(xb, yb).wait_to_read()  # compile outside the timed window
    it = make_iter()
    t0 = time.perf_counter()
    n_stepped = 0
    for b in it:
        loss = tr.step(b.data[0], b.label[0])
        float(loss.asscalar())  # mxlint: disable=SRC001,SRC004
        n_stepped += 1
    stepped_s = time.perf_counter() - t0

    # -- bulk: prefetch thread + run-ahead window + lazy loss accumulation
    tr = build_trainer()
    tr.step(xb, yb).wait_to_read()  # compile outside the timed window
    pf = PrefetchToDeviceIter(make_iter(), sharding=tr.batch_sharding,
                              depth=2)
    tot = None
    t0 = time.perf_counter()
    n_bulk = 0
    with engine.bulk(depth):
        for b in pf:
            loss = tr.step(b.data[0], b.label[0])
            tot = loss if tot is None else tot + loss
            n_bulk += 1
    float(tot.asscalar())  # the window's one fetch
    bulk_s = time.perf_counter() - t0

    snap = tr.dispatch_stats.snapshot()
    out = {
        "train_loop_overlap_ratio": round(stepped_s / bulk_s, 3),
        "dispatch_depth": depth,
        "overlap_step_ms": round(step_s * 1000, 3),
        "overlap_feed_ms": round(feed_s * 1000, 3),
        "overlap_stepped_steps_per_sec": round(n_stepped / stepped_s, 2),
        "overlap_bulk_steps_per_sec": round(n_bulk / bulk_s, 2),
        "overlap_inflight_max": snap["inflight_max"],
        "overlap_dispatch_stall_s": snap["dispatch_stall_s"],
        "overlap_prefetch_slots_max": pf.live_slots_max,
        "overlap_prefetch_hbm_bound_bytes": pf.hbm_bound_bytes(),
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
