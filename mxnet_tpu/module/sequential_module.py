"""SequentialModule: chain modules, feeding outputs to the next
(reference: python/mxnet/module/sequential_module.py)."""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..io import DataDesc
from .base_module import BaseModule


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            need_labels = meta.get(self.META_TAKE_LABELS, False)
            lbl = label_shapes if need_labels else None
            grad = True if i > 0 else inputs_need_grad
            module.bind(cur_shapes, lbl, for_training=for_training,
                        inputs_need_grad=grad, force_rebind=force_rebind,
                        grad_req=grad_req)
            if meta.get(self.META_AUTO_WIRING, True) and \
                    i + 1 < len(self._modules):
                nxt = self._modules[i + 1].data_names
                cur_shapes = [DataDesc(n, s) for n, s in
                              zip(nxt, [o[1] for o in module.output_shapes])]
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params, allow_missing=True,
                               force_init=force_init, allow_extra=True)
        self.params_initialized = True

    def get_params(self):
        arg_params, aux_params = {}, {}
        for module in self._modules:
            a, x = module.get_params()
            arg_params.update(a)
            aux_params.update(x)
        return arg_params, aux_params

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        from ..io import DataBatch
        batch = data_batch
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            outs = module.get_outputs()
            label = data_batch.label if \
                self._metas[i + 1].get(self.META_TAKE_LABELS, False) else None
            batch = DataBatch(outs, label, pad=data_batch.pad,
                              index=data_batch.index)

    def backward(self, out_grads=None):
        for i in range(len(self._modules) - 1, -1, -1):
            module = self._modules[i]
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, lazy=False):
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels, lazy=lazy)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
