"""Module: symbol + executor + optimizer, the workhorse training API.

Reference: ``python/mxnet/module/module.py`` (bind ``:364``,
init_optimizer ``:473``, update ``:643``) over DataParallelExecutorGroup
(``executor_group.py:143``).

TPU-native: one Executor spanning all requested devices — binding over a
context *list* builds a jax Mesh and GSPMD shards the batch across it, so
the executor-group/KVStore-'device' machinery of the reference collapses
into compiler-inserted ICI collectives.  The KVStore path is kept for
``update_on_kvstore`` semantics (server-side optimizer parity) and for
multi-host (`dist_*`) training.
"""
from __future__ import annotations

import logging

import numpy as _np

from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..context import cpu
from ..executor import Executor
from ..initializer import InitDesc
from ..io import DataDesc
from ..ndarray import NDArray
from .base_module import BaseModule


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names) if data_names else []
        self._label_names = list(label_names) if label_names else []
        self._context = context if context is not None else cpu()
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        # group2ctxs: {ctx_group: PartitionSpec | Context} — consumed at
        # bind time into GSPMD shardings (reference: PlaceDevice pass)
        self._group2ctxs = group2ctxs

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._exec = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = False
        self._updater = None
        self._preload_opt_states = None
        self._data_shapes = None
        self._label_shapes = None
        self._monitor = None

    # ------------------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create from a saved checkpoint (reference: module.py Module.load)."""
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params_cache = (args, auxs)
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        if not self.binded:
            raise MXNetError("module not bound")
        return list(zip(self._output_names,
                        [o.shape for o in self._exec.outputs]))

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                       for d in data_shapes]
        label_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                        for d in (label_shapes or [])]
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        shapes = {d.name: d.shape for d in data_shapes + label_shapes}
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and n not in self._fixed_param_names:
                req[n] = grad_req if for_training else "null"
            elif inputs_need_grad and n in self._data_names:
                req[n] = grad_req
            else:
                req[n] = "null"
        type_dict = {d.name: d.dtype for d in data_shapes + label_shapes}
        self._exec = Executor.simple_bind(
            self._symbol, self._context, grad_req=req, type_dict=type_dict,
            shapes=shapes,
            data_names=self._data_names + self._label_names + self._state_names,
            group2ctx=self._group2ctxs)
        if shared_module is not None and shared_module._exec is not None:
            # share parameter arrays (BucketingModule memory sharing)
            for n in self._param_names:
                if n in shared_module._exec.arg_dict:
                    self._exec.arg_dict[n] = shared_module._exec.arg_dict[n]
            for n in self._aux_names:
                if n in shared_module._exec.aux_dict:
                    self._exec.aux_dict[n] = shared_module._exec.aux_dict[n]
        self.binded = True
        cached = getattr(self, "_arg_params_cache", None)
        if cached is not None:
            self.set_params(*cached)
            self._arg_params_cache = None

    # ------------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        attr_dict = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
                arr._set_data(nd.array(src.asnumpy() if isinstance(src, NDArray)
                                       else src)._data.astype(arr.dtype))
            elif initializer is not None:
                desc = InitDesc(name, attr_dict.get(name))
                initializer(desc, arr)
            elif not allow_missing and arg_params is not None:
                raise MXNetError("missing parameter %r" % name)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                src = aux_params[name]
                arr._set_data(nd.array(src.asnumpy() if isinstance(src, NDArray)
                                       else src)._data.astype(arr.dtype))
            elif initializer is not None:
                desc = InitDesc(name, attr_dict.get(name))
                initializer(desc, arr)
        # initializers write host-side values; restore device placement
        self._exec._place_arrays()
        self.params_initialized = True

    def get_params(self):
        if not self.binded:
            raise MXNetError("module not bound")
        arg_params = {n: self._exec.arg_dict[n].copy()
                      for n in self._param_names}
        aux_params = {n: self._exec.aux_dict[n].copy()
                      for n in self._aux_names}
        return arg_params, aux_params

    # ------------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if not self.params_initialized:
            raise MXNetError("init_params before init_optimizer")
        from ..model import _create_kvstore
        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, 1, {n: self._exec.arg_dict[n] for n in self._param_names})
        if isinstance(optimizer, str):
            batch_size = self._data_shapes[0].shape[0] if self._data_shapes \
                else 1
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            # reference module.py:497 — default grad rescale by batch size
            optimizer_params.setdefault("rescale_grad", 1.0 / max(batch_size, 1))
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore and kvstore is not None
        if kvstore is not None:
            # init kv with parameter arrays keyed by index
            for i, n in enumerate(self._param_names):
                kvstore.init(i, self._exec.arg_dict[n])
            if self._update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        if not self._update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for desc, arr in zip(self._data_names, data_batch.data):
            feeds[desc] = arr
        if self._label_names and data_batch.label is not None:
            for desc, arr in zip(self._label_names, data_batch.label):
                feeds[desc] = arr
        # shape change (last batch / bucketing) → jit recompile is cached
        self._exec.forward(is_train=is_train, **feeds)
        if self._monitor is not None:
            self._monitor.observe(self)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply gradients (reference: module.py:643 →
        model.py _update_params(_on_kvstore))."""
        if not self.optimizer_initialized:
            raise MXNetError("init_optimizer before update")
        if self._kvstore is not None:
            for i, n in enumerate(self._param_names):
                g = self._exec.grad_dict.get(n)
                if g is None:
                    continue
                self._kvstore.push(i, g)
                if self._update_on_kvstore:
                    self._kvstore.pull(i, self._exec.arg_dict[n])
                else:
                    self._kvstore.pull(i, g)
            if self._update_on_kvstore:
                return
        for i, n in enumerate(self._param_names):
            g = self._exec.grad_dict.get(n)
            if g is None:
                continue
            self._updater(i, g, self._exec.arg_dict[n])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    # ------------------------------------------------------------------
    def jit_cache_keys(self):
        """Executed jit signatures of the bound executor (one per compiled
        program).  The serving layer snapshots this after bucket warmup and
        asserts the set never grows under steady-state traffic."""
        if not self.binded:
            return set()
        return self._exec.jit_cache_keys()

    def jit_cache_size(self):
        """Number of compiled program variants behind this module."""
        if not self.binded:
            return 0
        return self._exec.jit_cache_size()

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True")
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, lazy=False):
        # lazy: park the device-resident outputs instead of asnumpy-ing
        # them per batch (fit's hot loop) — the metric drains at its next
        # read (Speedometer tick / epoch log), the flush boundary
        if lazy and hasattr(eval_metric, "update_lazy"):
            eval_metric.update_lazy(labels, self.get_outputs())
        else:
            eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------------
    def save_optimizer_states(self, fname):
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        self._monitor = mon
        mon.install(self)

    def reshape(self, data_shapes, label_shapes=None):
        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                                  for d in label_shapes]
        # jit recompiles per shape automatically; nothing to do eagerly
