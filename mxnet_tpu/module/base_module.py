"""BaseModule: the high-level train/predict interface.

Reference: ``python/mxnet/module/base_module.py`` — ``fit`` at ``:399``
(epoch loop ``:500-560``), ``score``, ``predict``, ``forward_backward``.
The TPU rebuild keeps the exact API so reference training scripts
(`example/image-classification/common/fit.py`) run unchanged; under the
hood a bound module is one jitted XLA program per (train, shapes) key.
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from .. import metric as _metric
from ..base import MXNetError


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------------------
    # abstract surface (implemented by Module / BucketingModule)
    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, lazy=False):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # ------------------------------------------------------------------
    # generic functionality
    # ------------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def lint(self, disable=(), check_consts=True):
        """Static graph lint of this module's symbol (mxnet_tpu.analysis).

        Uses the bound data/label shapes when available so the
        trace-based checks (oversized constants) can run; callable before
        bind too, with the shape-dependent rules skipped."""
        if self._symbol is None:
            raise MXNetError("module has no symbol to lint")
        shapes = {}
        for desc in (getattr(self, "_data_shapes", None) or []):
            shapes[desc.name] = desc.shape
        for desc in (getattr(self, "_label_shapes", None) or []):
            shapes[desc.name] = desc.shape
        return self._symbol.lint(shapes=shapes or None, disable=disable,
                                 check_consts=check_consts)

    def cost_report(self, shapes=None):
        """Static cost/memory model (mxcost) of this module's forward at
        the bound data/label shapes (or explicit ``shapes``): FLOPs,
        bytes, transfer, peak HBM — no execution, no device.  Returns a
        ``CostReport`` or None when shapes are unknown/untraceable."""
        if self._symbol is None:
            raise MXNetError("module has no symbol to analyze")
        if shapes is None:
            shapes = {}
            for desc in (getattr(self, "_data_shapes", None) or []):
                shapes[desc.name] = desc.shape
            for desc in (getattr(self, "_label_shapes", None) or []):
                shapes[desc.name] = desc.shape
        if not shapes:
            return None
        return self._symbol.cost_report(shapes=shapes)

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0, sparse_row_id_fn=None):
        """Evaluate on a DataIter (reference: base_module.py score)."""
        if not self.binded or not self.params_initialized:
            raise MXNetError("module must be binded and initialized")
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(params)
        if score_end_callback is not None:
            params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Run inference, concatenating outputs (reference: predict)."""
        from .. import ndarray as nd
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concatenate([o[i] for o in output_list], axis=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            yield (outs, nbatch, eval_batch)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The training loop (reference: base_module.py:399)."""
        if num_epoch is None:
            raise ValueError("num_epoch must be specified")
        if initializer is None:
            from ..initializer import Uniform
            initializer = Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                # lazy: the metric parks the device-resident outputs and
                # drains at its next read — a Speedometer tick or the
                # epoch log below — instead of an asnumpy sync per step
                self.update_metric(eval_metric, data_batch.label, lazy=True)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    def install_monitor(self, mon):
        raise NotImplementedError

    # convenience accessors
    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError


class BatchEndParam:
    """Callback payload (reference: model.py BatchEndParam namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return x
    return [x]
