"""BucketingModule: variable-length sequence training via per-bucket
executors sharing parameters.

Reference: ``python/mxnet/module/bucketing_module.py`` — one Module per
bucket key, memory shared with the largest bucket; used by the RNN/speech
examples (``stt_bucketing_module.py``) and ``docs/faq/bucketing.md``.

TPU-native: each bucket is a separate jit specialization (XLA compiles per
shape and caches), while parameter NDArrays are *shared handles* across
bucket Modules — so there is no copying on bucket switch, exactly like the
reference's shared memory pool but without the manual pooling.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise ValueError("default_bucket_key must be given")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    @property
    def symbol(self):
        return self._curr_module._symbol if self._curr_module else None

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    # ------------------------------------------------------------------
    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names, label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names)

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes,
                        for_training=self.for_training,
                        shared_module=self._buckets.get(
                            self._default_bucket_key))
            if self._buckets.get(self._default_bucket_key) is not None and \
                    self._buckets[self._default_bucket_key].params_initialized:
                module.params_initialized = True
                opt_mod = self._buckets[self._default_bucket_key]
                if opt_mod.optimizer_initialized:
                    module._optimizer = opt_mod._optimizer
                    module._updater = opt_mod._updater
                    module._kvstore = opt_mod._kvstore
                    module._update_on_kvstore = opt_mod._update_on_kvstore
                    module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.switch_bucket(self._default_bucket_key, data_shapes, label_shapes)
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._buckets[self._default_bucket_key].init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                mod.params_initialized = True
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._buckets[self._default_bucket_key].init_optimizer(
            kvstore=kvstore, optimizer=optimizer,
            optimizer_params=optimizer_params, force_init=force_init)
        default = self._buckets[self._default_bucket_key]
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                mod._optimizer = default._optimizer
                mod._updater = default._updater
                mod._kvstore = default._kvstore
                mod._update_on_kvstore = default._update_on_kvstore
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        data_shapes = data_batch.provide_data or \
            [(n, a.shape) for n, a in zip(
                self._buckets[self._default_bucket_key].data_names,
                data_batch.data)]
        label_shapes = data_batch.provide_label
        if label_shapes is None and data_batch.label:
            label_shapes = [(n, a.shape) for n, a in zip(
                self._buckets[self._default_bucket_key].label_names,
                data_batch.label)]
        self.switch_bucket(data_batch.bucket_key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, lazy=False):
        self._curr_module.update_metric(eval_metric, labels, lazy=lazy)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)
