"""Device contexts mapped onto jax devices.

Reference: ``python/mxnet/context.py:29`` (Context with devtype ids
cpu/gpu/cpu_pinned/cpu_shared).  Here the accelerator is the TPU: ``mx.tpu(i)``
is the native device, ``mx.gpu(i)`` is kept as a compatibility alias so
reference scripts run unchanged, and ``cpu_pinned``/``cpu_shared`` collapse to
host memory (XLA manages transfer pinning itself).
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]


class Context:
    """A device context.

    Usable as ``with mx.tpu(0):`` to set the default device for array
    creation, matching reference semantics (context.py:119 ``__enter__``).
    """

    _default_ctx = threading.local()

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        Context._default_ctx.value = self._old_ctx

    # -- jax integration ---------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _backend_devices("cpu")
            if not devs:
                # no host backend registered (JAX_PLATFORMS pinned to an
                # accelerator): context is advisory in this design — every
                # array is a jax array — so fall through to the accelerator
                devs = _accelerator_devices()
        else:
            devs = _accelerator_devices()
        if not devs:
            raise RuntimeError("no %s devices available" % self.device_type)
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Release cached device memory (reference frees the GPU pool)."""
        # XLA owns the HBM allocator; nothing to do but keep the API.
        return None


def _backend_devices(platform):
    """Addressable devices of a platform — under multi-host jax the global
    list contains other hosts' (non-addressable) devices; placement must
    use this process's own (reference: each worker owns its GPUs)."""
    try:
        devs = jax.devices(platform)
    except RuntimeError:
        return []
    if jax.process_count() > 1:
        devs = [d for d in devs if d.process_index == jax.process_index()]
    return devs


_ACCEL_CACHE = None


def _accelerator_devices():
    """Local non-CPU jax devices; falls back to CPU if none (host testing)."""
    global _ACCEL_CACHE
    if _ACCEL_CACHE is None:
        devs = [d for d in jax.local_devices() if d.platform != "cpu"]
        _ACCEL_CACHE = devs if devs else _backend_devices("cpu")
    return _ACCEL_CACHE


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Compatibility alias: reference scripts use mx.gpu(); maps to the TPU."""
    return Context("tpu", device_id)


def num_tpus():
    """This process's accelerator count — consistent with Context placement
    (jax_device/list_gpus resolve locally under multi-host)."""
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return len(devs)


def num_gpus():
    return num_tpus()


_IMPLICIT_DEFAULT = None


def _implicit_default():
    """Default context follows jax's default backend: cpu() in CPU builds,
    tpu(0) when an accelerator owns the default device.  Keeping the two in
    agreement avoids mixed-device programs when users never pass ctx (the
    reference defaults to cpu() because its CPU build has no choice)."""
    global _IMPLICIT_DEFAULT
    if _IMPLICIT_DEFAULT is None:
        try:
            platform = jax.default_backend()
        except Exception:
            platform = "cpu"
        _IMPLICIT_DEFAULT = Context("cpu" if platform == "cpu" else "tpu", 0)
    return _IMPLICIT_DEFAULT


def current_context():
    cur = getattr(Context._default_ctx, "value", None)
    return cur if cur is not None else _implicit_default()


def gpu_memory_info(device_id=0):
    """(free, total) bytes on the accelerator (reference: context.py
    gpu_memory_info over cudaMemGetInfo; here the XLA allocator stats —
    the Storage-manager stats facade, SURVEY.md §2.1)."""
    devs = [d for d in _accelerator_devices() if d.platform != "cpu"]
    if not devs:
        raise RuntimeError("no accelerator device present")
    if device_id >= len(devs):
        raise ValueError("device_id %d out of range (%d local accelerators)"
                         % (device_id, len(devs)))
    stats = devs[device_id].memory_stats() or {}
    if "bytes_limit" not in stats:
        raise RuntimeError("memory stats unavailable for %r"
                           % devs[device_id])
    total = stats["bytes_limit"]
    return (total - stats.get("bytes_in_use", 0), total)
